"""Tenant identity: the contextvar spine of the isolation plane
(docs/robustness.md "Tenant isolation").

Every protection in the overload armor (admission slots, cache byte
budgets, hedge budgets) is meaningless against a hostile NEIGHBOR
unless the server knows which customer a request belongs to.  Identity
is derived per request: the index name by default (each index is a
tenant — the natural unit of blast radius), overridable with an
explicit ``X-Pilosa-Tpu-Tenant`` token for deployments that map many
indexes to one customer.  The token grammar is strict and validated at
the edge — garbage, oversize, or empty tokens are a clean 400, never an
exception — because the tenant name becomes a metrics label, a journal
field, and a queue key.

The active tenant rides a contextvar exactly like utils/deadline.py
and utils/profile.py: the HTTP handler activates it for the whole
request, the fan-out pool re-installs context via Tracer.task, and deep
layers (admission, result cache, HBM budget, hedge loop) read
``current()`` with one contextvar get.  An EXPLICIT token additionally
propagates on outbound internal hops (the coordinator's fan-out RPCs
carry the header) so a peer's internal admission pool attributes the
work to the same tenant; derived identities need no header — the peer
re-derives the same name from the index in the path.

``REGISTRY`` is the process-wide per-tenant accounting surface
(qps/p99/shed/hedge-denied/quota columns at /debug/vars "tenants" and
the /debug/cluster rollup), LRU-capped so hostile identifier churn
cannot grow it without bound."""

from __future__ import annotations

import contextvars
import re
import time
from collections import OrderedDict, deque
from contextlib import contextmanager

from .locks import make_lock

TENANT_HEADER = "X-Pilosa-Tpu-Tenant"
# Token grammar: short, printable, metrics-safe.  The name lands in
# stats series / journal events / debug tables, so the charset is the
# metrics charset, not "whatever fits in an HTTP header".
TENANT_MAX_LEN = 64
_TOKEN_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]*$")

DEFAULT_TENANT = "default"


class TenantError(ValueError):
    """Malformed tenant token (HTTP 400 at the handler)."""


def validate_token(token: str) -> str:
    """The validated token, or TenantError.  Never raises anything
    else — the fuzz contract: arbitrary header bytes are a clean 400."""
    if not isinstance(token, str) or not token:
        raise TenantError("tenant token must be a non-empty string")
    if len(token) > TENANT_MAX_LEN:
        raise TenantError(
            f"tenant token exceeds {TENANT_MAX_LEN} characters")
    if not _TOKEN_RE.match(token):
        raise TenantError(
            "tenant token must match [A-Za-z0-9][A-Za-z0-9_.-]* "
            "(letters, digits, '_', '.', '-'; leading alphanumeric)")
    return token


def derive(header_value: str | None, index: str | None
           ) -> tuple[str, bool]:
    """(tenant, explicit) for one request: the validated header token
    when present (explicit — forwarded on internal hops), else the
    index name, else the shared default bucket."""
    if header_value is not None:
        return validate_token(header_value), True
    if index:
        return str(index), False
    return DEFAULT_TENANT, False


def parse_weights(spec: str) -> dict[str, float]:
    """``"analytics:4,batch:1"`` -> {"analytics": 4.0, "batch": 1.0}.
    Unlisted tenants weigh 1.0; weights clamp to a small positive floor
    at use time so a zero/negative entry cannot stall its queue."""
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, w = part.partition(":")
        if not sep:
            raise TenantError(
                f"tenant weight {part!r} must be name:weight")
        try:
            out[validate_token(name.strip())] = float(w)
        except ValueError as e:
            raise TenantError(f"bad tenant weight {part!r}: {e}") from None
    return out


# -- request context ---------------------------------------------------------

# (name, explicit) — None outside any request (background work stays
# unattributed rather than polluting the default bucket's accounting)
_CTX: contextvars.ContextVar[tuple[str, bool] | None] = \
    contextvars.ContextVar("ptpu-tenant", default=None)


def context() -> tuple[str, bool] | None:
    """The raw (name, explicit) context for cross-thread hand-off:
    Tracer.task captures it alongside the trace context and re-installs
    both in pool workers, so fan-out RPCs keep the request's tenant."""
    return _CTX.get()


def current() -> str:
    """The active request's tenant (the shared default bucket when no
    tenant context is active — bare executors, background threads)."""
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else DEFAULT_TENANT


def current_or_none() -> str | None:
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else None


def header_value() -> str | None:
    """The header to forward on an outbound internal hop: only an
    EXPLICIT token propagates (a derived identity is re-derived from
    the index name on the peer — same answer, no header)."""
    ctx = _CTX.get()
    return ctx[0] if ctx is not None and ctx[1] else None


@contextmanager
def activate(name: str | None, explicit: bool = False):
    """Install ``name`` as the current tenant; None is a passthrough
    (the deadline.activate convention)."""
    if name is None:
        yield
        return
    token = _CTX.set((name, explicit))
    try:
        yield
    finally:
        _CTX.reset(token)


# -- process-wide per-tenant accounting --------------------------------------

MAX_TENANTS = 128       # registry LRU cap (identifier-churn armor)
LATENCY_RING = 256      # per-tenant latency samples for p50/p99


class TenantRegistry:
    """Per-tenant request/shed/hedge/quota counters + a small latency
    ring — the single source for the /debug/vars "tenants" table and
    the fleet rollup's per-tenant columns."""

    def __init__(self):
        self._lock = make_lock("tenant-registry")
        self._tenants: OrderedDict[str, dict] = OrderedDict()
        self.evicted = 0

    def _slot(self, tenant: str) -> dict:
        st = self._tenants.get(tenant)
        if st is None:
            while len(self._tenants) >= MAX_TENANTS:
                self._tenants.popitem(last=False)
                self.evicted += 1
            st = self._tenants[tenant] = {
                "requests": 0, "errors": 0, "shed": 0,
                "hedgeDenied": 0, "quotaEvicts": 0,
                "quotaEvictBytes": 0, "busyS": 0.0,
                "lat": deque(maxlen=LATENCY_RING),
                "sheds_by_pool": {}, "t0": time.monotonic(),
            }
        else:
            self._tenants.move_to_end(tenant)
        return st

    def note_request(self, tenant: str, dur_s: float, status: int):
        with self._lock:
            st = self._slot(tenant)
            st["requests"] += 1
            if status >= 400:
                st["errors"] += 1
            st["busyS"] += dur_s
            st["lat"].append(dur_s)

    def note_shed(self, tenant: str, pool: str):
        with self._lock:
            st = self._slot(tenant)
            st["shed"] += 1
            st["sheds_by_pool"][pool] = \
                st["sheds_by_pool"].get(pool, 0) + 1

    def note_hedge_denied(self, tenant: str):
        with self._lock:
            self._slot(tenant)["hedgeDenied"] += 1

    QUOTA_EVENT_MIN_S = 1.0  # journal rate limit per tenant

    def note_quota_evict(self, tenant: str, nbytes: int):
        emit_event = False
        with self._lock:
            st = self._slot(tenant)
            st["quotaEvicts"] += 1
            st["quotaEvictBytes"] += int(nbytes)
            # quota-breach journal entry, rate-limited per tenant (a
            # churning flood is one timeline entry per interval with the
            # counters carrying the magnitude); emitted OUTSIDE the
            # registry lock — the journal takes its own
            now = time.monotonic()
            last = st.get("quota_event_at")
            if last is None or now - last >= self.QUOTA_EVENT_MIN_S:
                st["quota_event_at"] = now
                emit_event = True
        if emit_event:
            from .events import EVENTS
            EVENTS.emit("tenant.quota", tenant=tenant,
                        evictedBytes=int(nbytes))

    def clear(self):
        with self._lock:
            self._tenants.clear()
            self.evicted = 0

    def snapshot(self) -> dict:
        """tenant -> qps/p50/p99/shed/hedge/quota columns (qps over the
        tenant's own observation window)."""
        out = {}
        with self._lock:
            now = time.monotonic()
            for name, st in self._tenants.items():
                lat = sorted(st["lat"])
                window = max(now - st["t0"], 1e-6)
                row = {
                    "requests": st["requests"],
                    "errors": st["errors"],
                    "qps": round(st["requests"] / window, 3),
                    "shed": st["shed"],
                    "shedByPool": dict(st["sheds_by_pool"]),
                    "hedgeDenied": st["hedgeDenied"],
                    "quotaEvicts": st["quotaEvicts"],
                    "quotaEvictBytes": st["quotaEvictBytes"],
                }
                if lat:
                    row["p50Ms"] = round(
                        lat[len(lat) // 2] * 1e3, 3)
                    row["p99Ms"] = round(
                        lat[min(len(lat) - 1,
                                int(len(lat) * 0.99))] * 1e3, 3)
                out[name] = row
        return out


REGISTRY = TenantRegistry()


# -- hedge budgets -----------------------------------------------------------

class HedgeBudget:
    """Per-tenant token bucket gating speculative (hedged) reads: one
    tenant's straggler storm must not amplify ITS load onto the whole
    fleet.  ``rate`` tokens refill per second with an equal burst
    capacity; 0 disables the budget (every hedge admitted).  Buckets
    are LRU-capped like the registry."""

    def __init__(self, rate: float = 0.0):
        self.rate = max(float(rate), 0.0)
        self._lock = make_lock("hedge-budget")
        self._buckets: OrderedDict[str, list] = OrderedDict()
        self.denied = 0

    def try_take(self, tenant: str, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        now = time.monotonic()
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                while len(self._buckets) >= MAX_TENANTS:
                    self._buckets.popitem(last=False)
                b = self._buckets[tenant] = [self.rate, now]
            else:
                self._buckets.move_to_end(tenant)
                b[0] = min(self.rate, b[0] + (now - b[1]) * self.rate)
                b[1] = now
            if b[0] >= n:
                b[0] -= n
                return True
            self.denied += 1
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"rate": self.rate, "denied": self.denied,
                    "tenants": {t: round(b[0], 3)
                                for t, b in self._buckets.items()}}
