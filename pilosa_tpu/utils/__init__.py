"""Cross-cutting utilities: logger, stats, tracing (reference logger/,
stats/, tracing/)."""

from .logger import Logger, NopLogger  # noqa: F401
from .stats import NopStatsClient, StatsClient  # noqa: F401
from .tracing import GLOBAL_TRACER, NopTracer, Tracer  # noqa: F401
