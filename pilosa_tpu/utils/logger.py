"""Logger (reference logger/logger.go:25-107 Logger iface +
std/verbose/nop impls)."""

from __future__ import annotations

import sys
import time


class Logger:
    def __init__(self, verbose: bool = False, stream=None):
        self.verbose = verbose
        self.stream = stream or sys.stderr

    def _emit(self, level: str, msg: str):
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        self.stream.write(f"{ts} {level} {msg}\n")
        self.stream.flush()

    def info(self, msg: str):
        self._emit("INFO", msg)

    def debug(self, msg: str):
        if self.verbose:
            self._emit("DEBUG", msg)

    def error(self, msg: str):
        self._emit("ERROR", msg)


class NopLogger(Logger):
    def _emit(self, level: str, msg: str):
        pass
