"""Logger (reference logger/logger.go:25-107 Logger iface +
std/verbose/nop impls)."""

from __future__ import annotations

import sys
import time


class Logger:
    def __init__(self, verbose: bool = False, stream=None):
        self.verbose = verbose
        self.stream = stream or sys.stderr

    def _emit(self, level: str, msg: str):
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        self.stream.write(f"{ts} {level} {msg}\n")
        self.stream.flush()

    def info(self, msg: str):
        self._emit("INFO", msg)

    def debug(self, msg: str):
        if self.verbose:
            self._emit("DEBUG", msg)

    def error(self, msg: str):
        self._emit("ERROR", msg)

    def event(self, name: str, **fields):
        """Structured log line — ``<ts> INFO <name> k=v k=v ...`` with
        stable key order — so operators can grep/join machine-readably.
        The slow-query log emits these with ``trace=<id>``, correlating
        log lines to /debug/traces (docs/observability.md)."""
        parts = " ".join(
            f"{k}={v!r}" if isinstance(v, str) else f"{k}={v}"
            for k, v in fields.items())
        self._emit("INFO", f"{name} {parts}" if parts else name)


class NopLogger(Logger):
    def _emit(self, level: str, msg: str):
        pass
