"""Per-request degraded-state accumulator (docs/robustness.md
"Corruption quarantine").

A query that touches quarantined fragments still answers — those
fragments contribute EMPTY rows — but the response must say so: silent
partial answers are how corruption poisons downstream systems.  The HTTP
handler opens a collector around query execution; the coordinator notes
peer-reported quarantine counts as fan-out responses are consumed (on
the request thread), the handler adds the local count, and the response
carries a ``degraded`` object when the total is non-zero.

Contextvar-based like utils/profile.py: zero cost and inert when no
collector is active (internal hops, background work).
"""

from __future__ import annotations

import contextlib
import contextvars

_collector: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "ptpu-degraded", default=None)


@contextlib.contextmanager
def collect():
    """Activate a fresh accumulator for this request; yields the dict
    that note() mutates."""
    acc = {"quarantinedFragments": 0}
    token = _collector.set(acc)
    try:
        yield acc
    finally:
        _collector.reset(token)


def note(n: int = 1):
    """Record n quarantined fragments touched by the current request
    (no-op outside a collector)."""
    acc = _collector.get()
    if acc is not None and n:
        acc["quarantinedFragments"] += n
