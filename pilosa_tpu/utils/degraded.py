"""Per-request degraded-state accumulator (docs/robustness.md
"Corruption quarantine" and "Partial results").

A query that touches quarantined fragments still answers — those
fragments contribute EMPTY rows — but the response must say so: silent
partial answers are how corruption poisons downstream systems.  The HTTP
handler opens a collector around query execution; the coordinator notes
peer-reported quarantine counts as fan-out responses are consumed (on
the request thread), the handler adds the local count, and the response
carries a ``degraded`` object when the total is non-zero.

The same collector carries the PARTIAL-RESULTS contract
(``?partialResults=true`` / the ``partial-results`` server default): a
read fan-out whose shards are truly unservable — every replica dead,
partitioned, or exhausted — may degrade to a partial answer instead of
failing, but ONLY when the collector allows it, and the response's
``degraded`` object then names exactly the missing shards and the nodes
that failed to serve them, so a caller can never mistake partial for
complete.

Contextvar-based like utils/profile.py: zero cost and inert when no
collector is active (internal hops, background work).
"""

from __future__ import annotations

import contextlib
import contextvars

_collector: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "ptpu-degraded", default=None)


@contextlib.contextmanager
def collect(allow_partial: bool = False):
    """Activate a fresh accumulator for this request; yields the dict
    that note()/note_missing() mutate.  ``allow_partial``: the caller
    opted into partial results (?partialResults=true or the
    partial-results server default) — without it, unservable shards
    still fail the query loudly."""
    acc = {"quarantinedFragments": 0, "missingShards": {},
           "missingNodes": set(), "allowPartial": bool(allow_partial)}
    token = _collector.set(acc)
    try:
        yield acc
    finally:
        _collector.reset(token)


def note(n: int = 1):
    """Record n quarantined fragments touched by the current request
    (no-op outside a collector)."""
    acc = _collector.get()
    if acc is not None and n:
        acc["quarantinedFragments"] += n


def partial_allowed() -> bool:
    """May the current request degrade to a partial answer?  False
    outside a collector (internal hops, background work): the fan-out
    then fails loudly, exactly the pre-partial behavior."""
    acc = _collector.get()
    return bool(acc is not None and acc["allowPartial"])


def note_missing(index: str, shards, nodes=()):
    """Record shards the current request could NOT serve (every replica
    unavailable) and the nodes that failed to serve them.  The response
    builder turns these into ``degraded.missingShards`` /
    ``degraded.missingNodes`` — the exact-loss contract partial results
    stand on."""
    acc = _collector.get()
    if acc is None:
        return
    acc["missingShards"].setdefault(index, set()).update(
        int(s) for s in shards)
    acc["missingNodes"].update(nodes)


def is_partial() -> bool:
    """Did the current request actually lose shards?  (Used to keep a
    partial answer OUT of the result cache — a later healthy repeat
    must not serve the degraded answer.)"""
    acc = _collector.get()
    return bool(acc is not None and acc["missingShards"])


def is_degraded() -> bool:
    """Did the current request degrade in ANY way — lost shards OR
    quarantined fragments?  This is the result-cache fill guard:
    ``is_partial()`` alone would memoize a quarantined-degraded answer
    (empty rows standing in for poisoned fragments) and keep serving it
    after the fragments heal."""
    acc = _collector.get()
    return bool(acc is not None and (acc["missingShards"]
                                     or acc["quarantinedFragments"]))


def to_response(acc: dict) -> dict | None:
    """The wire ``degraded`` object for a finished collector, or None
    when the request was not degraded at all."""
    out = {}
    if acc["quarantinedFragments"]:
        out["quarantinedFragments"] = acc["quarantinedFragments"]
    if acc["missingShards"]:
        out["missingShards"] = {i: sorted(s)
                                for i, s in acc["missingShards"].items()}
        out["missingNodes"] = sorted(acc["missingNodes"])
    return out or None
