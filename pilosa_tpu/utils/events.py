"""Durable event journal: the cluster's state-transition timeline
(docs/observability.md "Cluster plane").

Every consequential state transition the system makes — a breaker
opening, a node flipping DOWN, a fragment entering quarantine, an
overlay handoff, a resize epoch, a retrace, backpressure engaging —
already logs a line or bumps a counter somewhere, but counters have no
order and log lines have no structure: reconstructing "what happened to
the fleet between 14:02 and 14:05" meant grepping N nodes' stderr.
This module gives those transitions one ordered, structured, queryable
home:

* a bounded in-process ring (``event-journal-size`` entries) served at
  ``GET /debug/events?since=<seq>`` — the cursor form the fleet rollup
  (parallel/rollup.py) uses to merge per-node journals into one fleet
  timeline on ``/debug/cluster``;
* an optional on-disk log (``event-log = true``): length+CRC framed
  JSON records, one frame per event (the PR 6 WAL frame discipline) so
  a torn tail is detected and truncated at a frame boundary on reopen.
  Events are telemetry, not acked data — the log is flushed per event
  but not fsynced, and a corrupt tail truncates instead of quarantining.

Every event carries a monotonically increasing per-process ``seq`` (the
``since`` cursor), a display-only wall stamp, the emitting node's id,
and the event's structured fields.  Emission must never fail the caller:
file errors count ``writeErrors`` and drop the disk copy only.

The event-name namespace is cataloged in docs/observability.md (the
``events-catalog`` markers) under the same two-way analyzer lint as the
metrics catalog (``event-names`` rule): an uncataloged emit site and a
dangling catalog row are both findings.
"""

from __future__ import annotations

import json
import os
import struct
import time
from collections import deque

from .durable import checksum
from .locks import make_lock

EVENT_LOG_MAGIC = b"PTPUEVT1"
_FRAME_HDR = struct.Struct("<II")  # payload length, crc32(payload)


def _wall_stamp() -> float: return time.time()  # display-only wall clock


class EventJournal:
    """Bounded ring of structured state-transition events + optional
    framed on-disk log.  One leaf lock guards the ring, the sequence
    counter, and the file handle; emission sites are rare state
    transitions, never per-query hot paths."""

    def __init__(self, size: int = 512):
        self.size = max(int(size), 1)
        self._ring: deque = deque(maxlen=self.size)
        self._lock = make_lock("events")
        self.seq = 0
        self.emitted = 0
        self.write_errors = 0
        # stamped onto every event so merged fleet timelines keep
        # attribution; the Server sets it (standalone emitters stay
        # unattributed rather than guessing)
        self.node_id: str | None = None
        self._fh = None
        self._path: str | None = None

    def resize(self, size: int):
        """Apply event-journal-size (most recent Server's config wins,
        like the launch ledger); keeps the newest entries."""
        size = max(int(size), 1)
        with self._lock:
            if size != self.size:
                self._ring = deque(self._ring, maxlen=size)
                self.size = size

    # -- on-disk log -------------------------------------------------------

    def open_log(self, path: str):
        """Open (or create) the framed on-disk log, truncating any torn
        tail at the last valid frame boundary.  Unlike the fragment WAL,
        mid-log corruption also truncates: events are telemetry — better
        a shortened history than a refused journal."""
        valid_end = len(EVENT_LOG_MAGIC)
        try:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    data = f.read()
                if not data.startswith(EVENT_LOG_MAGIC):
                    valid_end = len(EVENT_LOG_MAGIC)  # rewrite garbage
                else:
                    pos = len(EVENT_LOG_MAGIC)
                    while pos + _FRAME_HDR.size <= len(data):
                        ln, crc = _FRAME_HDR.unpack_from(data, pos)
                        end = pos + _FRAME_HDR.size + ln
                        if end > len(data) \
                                or checksum(data[pos + _FRAME_HDR.size:
                                                 end]) != crc:
                            break
                        pos = end
                    valid_end = pos
                fh = open(path, "r+b")
                fh.truncate(valid_end)
                fh.seek(valid_end)
                if valid_end == len(EVENT_LOG_MAGIC) \
                        and not data.startswith(EVENT_LOG_MAGIC):
                    fh.seek(0)
                    fh.truncate(0)
                    fh.write(EVENT_LOG_MAGIC)
            else:
                fh = open(path, "w+b")
                fh.write(EVENT_LOG_MAGIC)
            fh.flush()
        except OSError:
            # journaling is best-effort: a read-only data dir costs the
            # disk copy, never the ring or the emitting caller
            self.write_errors += 1
            return
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = fh
            self._path = path

    def close_log(self):
        with self._lock:
            fh, self._fh, self._path = self._fh, None, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    @staticmethod
    def read_log(path: str) -> list[dict]:
        """Decode a framed log's valid prefix (tests, offline forensic
        reads); stops at the first bad frame like open_log's truncation
        scan."""
        with open(path, "rb") as f:
            data = f.read()
        out: list[dict] = []
        if not data.startswith(EVENT_LOG_MAGIC):
            return out
        pos = len(EVENT_LOG_MAGIC)
        while pos + _FRAME_HDR.size <= len(data):
            ln, crc = _FRAME_HDR.unpack_from(data, pos)
            end = pos + _FRAME_HDR.size + ln
            payload = data[pos + _FRAME_HDR.size: end]
            if end > len(data) or checksum(payload) != crc:
                break
            out.append(json.loads(payload))
            pos = end
        return out

    # -- emission ----------------------------------------------------------

    def emit(self, name: str, **fields) -> dict:
        """Append one structured event; returns the stamped record.
        Never raises — a journal failure must not fail a breaker
        transition or a quarantine."""
        entry = {"event": name, "wall": round(_wall_stamp(), 3)}
        if self.node_id is not None:
            entry["node"] = self.node_id
        for k, v in fields.items():
            if v is not None:
                entry[k] = v
        with self._lock:
            self.seq += 1
            self.emitted += 1
            entry["seq"] = self.seq
            self._ring.append(entry)
            fh = self._fh
            if fh is not None:
                try:
                    payload = json.dumps(entry).encode()
                    # header + payload in ONE write (the group-commit
                    # frame discipline): a torn write truncates at a
                    # frame boundary, never interleaves
                    fh.write(_FRAME_HDR.pack(len(payload),
                                             checksum(payload)) + payload)
                    fh.flush()
                except (OSError, ValueError):
                    self.write_errors += 1
        return entry

    # -- queries -----------------------------------------------------------

    def since(self, seq: int, limit: int | None = None) -> list[dict]:
        """Events with seq > ``seq``, oldest first — the /debug/events
        cursor contract (a restarted reader passes 0 and gets whatever
        the ring still holds).  ``limit`` keeps the OLDEST entries: a
        cursor-advancing reader (the fleet rollup) resumes losslessly
        from the last seq it folded, instead of skipping the burst's
        middle forever."""
        with self._lock:
            out = [e for e in self._ring if e["seq"] > seq]
        if limit is not None and len(out) > limit:
            out = out[:max(limit, 0)]
        return out

    def last_seq(self) -> int:
        with self._lock:
            return self.seq

    def snapshot(self) -> dict:
        """GET /debug/events: config + counters + the ring, oldest
        first."""
        with self._lock:
            return {"size": self.size, "emitted": self.emitted,
                    "seq": self.seq, "writeErrors": self.write_errors,
                    "logPath": self._path,
                    "events": list(self._ring)}


# Process-wide singleton like FAULTS/COMPILES/LEDGER: one journal per
# process, resized/attached by the most recent Server's config.
EVENTS = EventJournal()


def emit(name: str, **fields) -> dict:
    """Module-level emission front door — ``events.emit("breaker.open",
    host=...)``.  The ``event-names`` analyzer rule collects these
    literals against the docs catalog."""
    return EVENTS.emit(name, **fields)
