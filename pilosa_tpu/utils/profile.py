"""Per-query profiles: an EXPLAIN ANALYZE for PQL
(docs/observability.md).

A ``QueryProfile`` is a stage-timing tree collected over one query's
lifetime — admission wait, parse/plan, result-cache lookup, batcher
queue + coalesce, per-shard-slice device exec with upload/evict counts,
per-peer fan-out RTT, reduce — threaded through the layers via a
contextvar like the deadline context (utils/deadline.py), so deep layers
add stages without new parameters on every dispatch signature.

The HTTP handler activates a profile for query routes whenever the
client asked for one (``?profile=true``, or the ``profile-default``
knob) OR the slow-query log is enabled (slow entries carry the tree);
the response embeds it only when requested.  Collection cost is a
handful of contextvar reads and dict appends per query — bench.py's
observability smoke leg asserts the profile-off serving path stays
within noise of the batching leg.

Stages nest on the owning request thread via ``stage()``; contributions
from OTHER threads (the dispatch batcher's queue wait, fused launches)
attach as finished events under a node captured at submit time
(``capture()`` + ``QueryProfile.event(..., node=...)``) — appends are
lock-protected, and the owner is blocked on the future while they
happen."""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager

from .locks import make_lock


class ProfileNode:
    __slots__ = ("name", "duration_s", "tags", "children")

    def __init__(self, name: str):
        self.name = name
        self.duration_s: float | None = None
        self.tags: dict = {}
        self.children: list[ProfileNode] = []

    def to_dict(self) -> dict:
        out = {"name": self.name,
               "durationMS": None if self.duration_s is None
               else round(self.duration_s * 1e3, 4)}
        if self.tags:
            out["tags"] = self.tags
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class QueryProfile:
    """One query's stage tree.  The stage stack is owned by the request
    thread; ``event()`` may be called from any thread."""

    def __init__(self):
        self.root = ProfileNode("query")
        self._t0 = time.perf_counter()
        self._stack = [self.root]
        self._lock = make_lock("profile")

    @contextmanager
    def stage(self, name: str):
        node = ProfileNode(name)
        with self._lock:
            self._stack[-1].children.append(node)
        self._stack.append(node)
        t0 = time.perf_counter()
        try:
            yield node
        finally:
            node.duration_s = time.perf_counter() - t0
            self._stack.pop()

    def event(self, name: str, duration_s: float,
              node: ProfileNode | None = None, **tags):
        """Append an already-finished stage under ``node`` (a node
        captured via capture()) or the current stack top."""
        ev = ProfileNode(name)
        ev.duration_s = duration_s
        ev.tags = tags
        with self._lock:
            (node if node is not None else self._stack[-1]) \
                .children.append(ev)

    def tag(self, key, value):
        self._stack[-1].tags[key] = value

    def current_node(self) -> ProfileNode:
        return self._stack[-1]

    def to_dict(self) -> dict:
        if self.root.duration_s is None:
            self.root.duration_s = time.perf_counter() - self._t0
        return self.root.to_dict()

    def finish(self):
        self.root.duration_s = time.perf_counter() - self._t0


_VAR: contextvars.ContextVar[QueryProfile | None] = \
    contextvars.ContextVar("pilosa_tpu_query_profile", default=None)


def current() -> QueryProfile | None:
    return _VAR.get()


@contextmanager
def activate(prof: QueryProfile | None):
    """Install ``prof`` for the with-block; activate(None) is a no-op
    passthrough (keeps call sites simple, like deadline.activate)."""
    if prof is None:
        yield None
        return
    token = _VAR.set(prof)
    try:
        yield prof
    finally:
        _VAR.reset(token)


@contextmanager
def stage(name: str):
    """Open a named stage on the active profile; yields the node (None
    when no profile is active — the hot-path cost is one contextvar
    read)."""
    prof = _VAR.get()
    if prof is None:
        yield None
        return
    with prof.stage(name) as node:
        yield node


def event(name: str, duration_s: float, **tags):
    prof = _VAR.get()
    if prof is not None:
        prof.event(name, duration_s, **tags)


def capture():
    """(profile, current node) for cross-thread contributions, or
    (None, None) when no profile is active."""
    prof = _VAR.get()
    if prof is None:
        return None, None
    return prof, prof.current_node()
