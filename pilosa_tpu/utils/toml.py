"""TOML loading with a py3.10 fallback.

``tomllib`` entered the stdlib in 3.11; on 3.10 the same parser exists
as the third-party ``tomli`` package (tomllib IS tomli, vendored).
Config loading (server.Config.from_toml, the CLI round-trip tests) goes
through this module so TOML support doesn't depend on the interpreter
minor version.
"""

from __future__ import annotations

try:
    import tomllib as _toml
except ModuleNotFoundError:  # Python < 3.11
    try:
        import tomli as _toml  # same parser, pre-stdlib packaging
    except ModuleNotFoundError:
        _toml = None


def load(fp) -> dict:
    """Parse a binary file object (tomllib.load signature)."""
    if _toml is None:
        raise ModuleNotFoundError(
            "TOML support needs Python >= 3.11 (tomllib) or the 'tomli' "
            "package on older interpreters")
    return _toml.load(fp)


def loads(text: str) -> dict:
    """Parse a TOML string (tomllib.loads signature)."""
    if _toml is None:
        raise ModuleNotFoundError(
            "TOML support needs Python >= 3.11 (tomllib) or the 'tomli' "
            "package on older interpreters")
    return _toml.loads(text)
