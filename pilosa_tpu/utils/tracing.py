"""Tracing: vendor-neutral Tracer/Span facade (reference
tracing/tracing.go:22-72) with an in-process recording tracer.

HTTP propagation uses a single `X-Pilosa-Tpu-Trace` header carrying the
trace id, so one distributed trace spans coordinator + remote nodes
(reference http/client.go:1043 inject / handler.go:231 extract)."""

from __future__ import annotations

import threading
import time
import uuid
from contextlib import contextmanager

TRACE_HEADER = "X-Pilosa-Tpu-Trace"


class Span:
    def __init__(self, tracer, name: str, trace_id: str, parent_id=None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:8]
        self.parent_id = parent_id
        # wall-clock start for display/correlation; durations come from a
        # perf_counter pair — a wall-clock step (NTP slew, manual set)
        # mid-span must not produce negative/garbage durations in
        # /debug/traces
        self.start = time.time()
        self._pc_start = time.perf_counter()
        self.end: float | None = None
        self.duration: float | None = None
        self.tags: dict = {}

    def set_tag(self, key, value):
        self.tags[key] = value

    def finish(self):
        self.duration = time.perf_counter() - self._pc_start
        self.end = self.start + self.duration
        self.tracer._record(self)

    def to_dict(self) -> dict:
        dur = self.duration if self.duration is not None \
            else time.perf_counter() - self._pc_start
        return {
            "name": self.name, "traceID": self.trace_id,
            "spanID": self.span_id, "parentID": self.parent_id,
            "start": self.start,
            "durationMS": dur * 1e3,
            "tags": self.tags,
        }


class Tracer:
    """Records the most recent spans in a ring buffer, exposed at
    /debug/traces."""

    def __init__(self, max_spans: int = 1000):
        self.max_spans = max_spans
        self._spans: list[Span] = []
        self._lock = threading.Lock()
        self._local = threading.local()

    def _record(self, span: Span):
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.max_spans:
                self._spans = self._spans[-self.max_spans:]

    def current_trace_id(self) -> str | None:
        return getattr(self._local, "trace_id", None)

    @contextmanager
    def span(self, name: str, trace_id: str | None = None):
        tid = trace_id or self.current_trace_id() or uuid.uuid4().hex[:16]
        parent = getattr(self._local, "span_id", None)
        s = Span(self, name, tid, parent)
        prev = (getattr(self._local, "trace_id", None),
                getattr(self._local, "span_id", None))
        self._local.trace_id = tid
        self._local.span_id = s.span_id
        try:
            yield s
        finally:
            s.finish()
            self._local.trace_id, self._local.span_id = prev

    def spans(self, trace_id: str | None = None) -> list[dict]:
        with self._lock:
            out = [s.to_dict() for s in self._spans]
        if trace_id:
            out = [s for s in out if s["traceID"] == trace_id]
        return out


GLOBAL_TRACER = Tracer()


class NopTracer(Tracer):
    def _record(self, span):
        pass
