"""Tracing: vendor-neutral Tracer/Span facade (reference
tracing/tracing.go:22-72) with an in-process recording tracer — Dapper-
style always-on distributed tracing (docs/observability.md).

HTTP propagation uses a single ``X-Pilosa-Tpu-Trace`` header carrying
``trace_id:parent_span_id`` (plus a ``:0`` suffix for unsampled traces),
so one distributed trace spans coordinator + remote nodes with CORRECT
parent links (reference http/client.go:1043 inject / handler.go:231
extract).  The active context rides a contextvar; worker threads that
cross a pool boundary (cluster fan-out, dispatch batcher, mesh prefetch)
re-install it via ``capture()``/``attach()`` or the ``task()`` wrapper —
a plain threading.local would silently drop it at every pool hop.

Remote nodes piggyback their span summaries on /internal/query responses
(``adopt()`` folds them into the coordinator's ring buffer), so
``GET /debug/traces?trace=<id>`` on the coordinator renders the whole
cluster tree."""

from __future__ import annotations

import contextvars
import random
import time
import uuid
from contextlib import contextmanager
from typing import NamedTuple

from .locks import make_lock

TRACE_HEADER = "X-Pilosa-Tpu-Trace"
# Requests tagged with this header are health/status probes: background
# traffic that must never pollute latency histograms or the slow-query
# log (server/handler.py checks it alongside the /status path).
PROBE_HEADER = "X-Pilosa-Tpu-Probe"


class TraceContext(NamedTuple):
    """The propagated part of a trace: ids + sampling decision + an
    optional collector list that finished span dicts are appended to
    (the remote side of the /internal/query span piggyback)."""

    trace_id: str
    span_id: str
    sampled: bool
    collect: list | None


def format_trace_header(trace_id: str, span_id: str,
                        sampled: bool = True) -> str:
    return f"{trace_id}:{span_id}" + ("" if sampled else ":0")


def parse_trace_header(value: str | None):
    """-> (trace_id, parent_span_id, sampled); (None, None, True) when
    absent.  Tolerates the legacy bare-trace-id form."""
    if not value:
        return None, None, True
    parts = value.split(":")
    tid = parts[0] or None
    parent = parts[1] if len(parts) > 1 and parts[1] else None
    sampled = not (len(parts) > 2 and parts[2] == "0")
    return tid, parent, sampled


_CTX: contextvars.ContextVar[TraceContext | None] = \
    contextvars.ContextVar("pilosa_tpu_trace_ctx", default=None)


class Span:
    def __init__(self, tracer, name: str, trace_id: str, parent_id=None,
                 sampled: bool = True, collect: list | None = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:8]
        self.parent_id = parent_id
        self.sampled = sampled
        self._collect = collect
        # wall-clock start for display/correlation; durations come from a
        # perf_counter pair — a wall-clock step (NTP slew, manual set)
        # mid-span must not produce negative/garbage durations in
        # /debug/traces
        # lint: allow(wall-clock) — display-only span start stamp;
        # durations come from the perf_counter pair below
        self.start = time.time()
        self._pc_start = time.perf_counter()
        self.end: float | None = None
        self.duration: float | None = None
        self.tags: dict = {}

    def set_tag(self, key, value):
        self.tags[key] = value

    def finish(self):
        self.duration = time.perf_counter() - self._pc_start
        self.end = self.start + self.duration
        if self.sampled:
            self.tracer._record(self)

    def to_dict(self) -> dict:
        dur = self.duration if self.duration is not None \
            else time.perf_counter() - self._pc_start
        return {
            "name": self.name, "traceID": self.trace_id,
            "spanID": self.span_id, "parentID": self.parent_id,
            "start": self.start,
            "durationMS": dur * 1e3,
            "tags": self.tags,
        }


class Tracer:
    """Records the most recent spans in a ring buffer, exposed at
    /debug/traces.  ``sample_rate`` (the ``trace-sample-rate`` knob)
    decides recording at each trace ROOT; the decision propagates to
    children and across the wire, so a trace is recorded everywhere or
    nowhere."""

    def __init__(self, max_spans: int = 1000):
        self.max_spans = max_spans
        self.sample_rate = 1.0
        self._spans: list = []  # Span objects or adopted remote dicts
        self._lock = make_lock("tracer")

    def _record(self, span: Span):
        if span._collect is not None:
            span._collect.append(span.to_dict())
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.max_spans:
                self._spans = self._spans[-self.max_spans:]

    def _record_raw(self, d: dict):
        with self._lock:
            self._spans.append(d)
            if len(self._spans) > self.max_spans:
                self._spans = self._spans[-self.max_spans:]

    # -- context -----------------------------------------------------------

    def current(self) -> TraceContext | None:
        return _CTX.get()

    def current_trace_id(self) -> str | None:
        ctx = _CTX.get()
        return ctx.trace_id if ctx is not None else None

    def capture(self) -> TraceContext | None:
        """The propagation context of this thread of execution; hand it
        to a worker thread and re-install with attach()."""
        return _CTX.get()

    @contextmanager
    def attach(self, ctx: TraceContext | None):
        """Install a captured context in the current thread (pool
        workers); attach(None) is a passthrough."""
        if ctx is None:
            yield
            return
        token = _CTX.set(ctx)
        try:
            yield
        finally:
            _CTX.reset(token)

    def task(self, fn, name: str | None = None, **span_tags):
        """Wrap ``fn`` for submission to a thread pool: the wrapper
        re-installs this thread's trace context in the worker and, when
        ``name`` is given, runs fn under a span of that name — so work
        fanned out to pools parents correctly instead of starting orphan
        traces."""
        from . import tenant as qtenant
        ctx = self.capture()
        # the tenant identity rides the same pool boundary: an outbound
        # fan-out RPC in a worker thread must still know WHOSE request
        # it serves (header forwarding, hedge budgets — utils/tenant.py)
        tctx = qtenant.context()
        if ctx is None and tctx is None:
            return fn

        def run(*args, **kwargs):
            with qtenant.activate(*(tctx or (None, False))):
                if ctx is None:
                    return fn(*args, **kwargs)
                with self.attach(ctx):
                    if name is None:
                        return fn(*args, **kwargs)
                    with self.span(name) as s:
                        for k, v in span_tags.items():
                            s.set_tag(k, v)
                        return fn(*args, **kwargs)

        return run

    def inject(self) -> str | None:
        """Header value for an outbound hop, or None when no trace is
        active (http/client.go:1043 inject)."""
        ctx = _CTX.get()
        if ctx is None:
            return None
        return format_trace_header(ctx.trace_id, ctx.span_id, ctx.sampled)

    # -- spans -------------------------------------------------------------

    @contextmanager
    def span(self, name: str, trace_id: str | None = None,
             parent_id: str | None = None, sampled: bool | None = None,
             collect: list | None = None):
        cur = _CTX.get()
        tid = trace_id or (cur.trace_id if cur is not None else None)
        if parent_id is None and trace_id is None and cur is not None:
            parent_id = cur.span_id
        if sampled is None:
            if trace_id is not None or cur is None:
                # trace root (or an explicit remote continuation without
                # a sampled flag): make the sampling decision here
                sampled = (self.sample_rate >= 1.0
                           or random.random() < self.sample_rate)
            else:
                sampled = cur.sampled
        if collect is None and cur is not None:
            collect = cur.collect
        if tid is None:
            tid = uuid.uuid4().hex[:16]
        s = Span(self, name, tid, parent_id, sampled=sampled,
                 collect=collect)
        token = _CTX.set(TraceContext(tid, s.span_id, sampled, collect))
        try:
            yield s
        finally:
            s.finish()
            _CTX.reset(token)

    def record_span(self, name: str, trace_id: str, parent_id: str | None,
                    duration_s: float, tags: dict | None = None,
                    collect: list | None = None):
        """Synthesize an already-finished span ENDING now (fused batch
        launches, other after-the-fact attributions) without a second
        wall-clock read: the constructor stamps now, then start shifts
        back by the duration.  ``collect`` (usually the captured
        context's) keeps the span riding the /internal/query piggyback
        like live spans do — without it a remote node's synthesized
        spans would be missing from the coordinator's cluster tree."""
        s = Span(self, name, trace_id, parent_id, collect=collect)
        s.start -= duration_s
        s._pc_start -= duration_s
        if tags:
            s.tags.update(tags)
        s.finish()

    def adopt(self, span_dicts):
        """Fold remote span summaries (piggybacked on /internal/query
        responses) into the ring buffer so /debug/traces renders the
        whole cluster tree."""
        if not span_dicts:
            return
        for d in span_dicts:
            if isinstance(d, dict) and "spanID" in d:
                self._record_raw(dict(d, remote=True))

    def spans(self, trace_id: str | None = None) -> list[dict]:
        with self._lock:
            out = [s if isinstance(s, dict) else s.to_dict()
                   for s in self._spans]
        if trace_id:
            out = [s for s in out if s["traceID"] == trace_id]
        return out

    def search(self, index: str | None = None,
               min_duration_s: float | None = None,
               status: int | None = None,
               limit: int = 100) -> list[dict]:
        """Trace summaries over the (bounded) retained ring, filtered by
        the ROOT span's tags — ``index``, minimum duration, final HTTP
        ``status`` (the handler stamps both onto its root span).  The
        drill-down path behind a histogram exemplar: find the spike's
        neighbors by index/duration, then fetch the full tree with
        ``?trace=<id>`` (docs/observability.md "Trace exemplars")."""
        all_spans = self.spans()
        by_trace: dict[str, int] = {}
        for s in all_spans:
            by_trace[s["traceID"]] = by_trace.get(s["traceID"], 0) + 1
        out = []
        for s in all_spans:
            if s.get("parentID") is not None or s.get("remote"):
                continue  # roots only (remote roots summarize elsewhere)
            tags = s.get("tags") or {}
            if index is not None and tags.get("index") != index:
                continue
            if status is not None and tags.get("status") != status:
                continue
            dur = s.get("durationMS")
            if min_duration_s is not None and \
                    (dur is None or dur < min_duration_s * 1e3):
                continue
            out.append({"traceID": s["traceID"], "name": s["name"],
                        "start": s.get("start"), "durationMS": dur,
                        "index": tags.get("index"),
                        "status": tags.get("status"),
                        "spans": by_trace[s["traceID"]]})
        out.sort(key=lambda t: t.get("start") or 0.0, reverse=True)
        return out[:max(limit, 1)]


GLOBAL_TRACER = Tracer()


class NopTracer(Tracer):
    def _record(self, span):
        pass

    def _record_raw(self, d):
        pass
