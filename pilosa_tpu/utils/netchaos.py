"""ChaosProxy: a TCP forwarder that injects NETWORK-level faults
between cluster nodes (docs/robustness.md "Network chaos").

The in-process failpoint registry (utils/faults.py) exercises error
*handling* paths, but it can't produce what real networks do: bytes
that arrive late, connections that die mid-response with an RST, peers
that accept a request and then go silent (half-open), or partitions
where one direction flows and the other doesn't.  Tests and game-days
park a ChaosProxy between nodes — the cluster's host list points at the
proxy, the proxy forwards to the real port — and arm faults with the
SAME ``name=mode[:arg][@match][#times]`` spec grammar as the failpoint
registry (utils/faults.py parse_spec), over the proxy's trigger sites:

    site      fires on
    -------   -------------------------------------------------------
    connect   every new inbound connection
    up        every chunk flowing client -> upstream (requests)
    down      every chunk flowing upstream -> client (responses)

and the network mode set:

    latency:<s>         sleep before forwarding each chunk (a straggling
                        but alive peer; arm with #times for a one-shot
                        stall)
    throttle:<bytes/s>  bandwidth cap: sleep len(chunk)/rate per chunk
    rst[:after_bytes]   once the site has forwarded >= after_bytes,
                        hard-close BOTH sockets with SO_LINGER(0) — the
                        peer sees a connection reset mid-stream
    blackhole           read and DISCARD chunks (half-open drop: the
                        sender believes the bytes went out, the receiver
                        blocks until its socket timeout); on ``connect``
                        the connection is accepted and never serviced
    partition           on ``connect``: accept and immediately RST (a
                        hard partition — definite, fast failure); on a
                        direction site it behaves like ``rst:0``

``@match`` substring-filters on the site key (``client_ip:port`` of the
inbound connection), ``#times`` disarms after that many triggers.
Asymmetric partitions are one-direction blackholes; full partitions are
``connect=partition`` plus :meth:`sever` to kill live flows.

Threading: one accept loop, two pump threads per connection.  Pure
stdlib, test/game-day infrastructure only — never on a serving path.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

from .faults import parse_spec
from .locks import make_lock

_SITES = ("connect", "up", "down")
_MODES = ("latency", "throttle", "rst", "blackhole", "partition")

# per-recv chunk; small enough that latency/throttle act per-segment,
# big enough that healthy forwarding is not syscall-bound
CHUNK = 64 << 10


class _NetFault:
    __slots__ = ("mode", "arg", "match", "times", "hits")

    def __init__(self, mode: str, arg: float, match: str | None,
                 times: int | None):
        self.mode = mode
        self.arg = arg
        self.match = match
        self.times = times
        self.hits = 0


def _hard_close(sock):
    """Close with SO_LINGER(1, 0): the kernel sends RST, not FIN — the
    peer sees a reset, exactly what a yanked cable / dead middlebox
    produces."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    """TCP forwarder ``listen_port -> target`` with armable faults."""

    def __init__(self, target_host: str, target_port: int,
                 listen_host: str = "localhost", listen_port: int = 0):
        self.target = (target_host, target_port)
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, listen_port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()[:2]
        self._faults: dict[str, _NetFault] = {}
        self._lock = make_lock("netchaos")
        self._closing = threading.Event()
        self._conns: set[tuple[socket.socket, socket.socket]] = set()
        # counters for assertions/snapshots (all under _lock)
        self.connections = 0
        self.bytes_up = 0
        self.bytes_down = 0
        self.rsts = 0
        self.dropped_bytes = 0
        self.refused = 0
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- arming ------------------------------------------------------------

    def arm(self, site: str, mode: str, arg: float = 0.0,
            match: str | None = None, times: int | None = None):
        if site not in _SITES:
            raise ValueError(f"unknown chaos site {site!r} "
                             f"(one of {_SITES})")
        if mode not in _MODES:
            raise ValueError(f"unknown chaos mode {mode!r} "
                             f"(one of {_MODES})")
        with self._lock:
            self._faults[site] = _NetFault(mode, arg, match, times)

    def configure(self, spec: str):
        """Arm from a ``site=mode[:arg][@match][#times];...`` spec —
        the shared faults.py grammar over the network mode set."""
        for site, mode, arg, match, times in parse_spec(spec):
            self.arm(site, mode, arg, match, times)

    def disarm(self, site: str | None = None):
        with self._lock:
            if site is None:
                self._faults.clear()
            else:
                self._faults.pop(site, None)

    def heal(self):
        """Disarm everything — the partition ends, traffic flows."""
        self.disarm()

    def sever(self):
        """RST every live connection (pair with ``connect=partition``
        for a full partition: existing flows die, new ones are
        refused)."""
        with self._lock:
            conns = list(self._conns)
        for a, b in conns:
            _hard_close(a)
            _hard_close(b)
        # severed pairs are gone — drop them so blackholed (pump-less)
        # connections don't accumulate in the set for the proxy's
        # lifetime (pump threads discard their own pair; this is the
        # only removal path a connect=blackhole entry ever gets)
        with self._lock:
            self._conns.difference_update(conns)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "target": f"{self.target[0]}:{self.target[1]}",
                "listen": self.address,
                "connections": self.connections,
                "bytesUp": self.bytes_up,
                "bytesDown": self.bytes_down,
                "rsts": self.rsts,
                "droppedBytes": self.dropped_bytes,
                "refused": self.refused,
                "armed": {s: {"mode": f.mode, "arg": f.arg,
                              "match": f.match, "timesLeft": f.times,
                              "hits": f.hits}
                          for s, f in self._faults.items()},
            }

    def close(self):
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.sever()

    # -- fault evaluation --------------------------------------------------

    def _fault(self, site: str, key: str,
               forwarded: int = 0) -> tuple[str, float] | None:
        """(mode, arg) if a fault fires for this site/key, else None.
        Consumes #times like the failpoint registry.  ``rst``'s byte
        threshold is checked HERE so an un-reached threshold neither
        counts a hit nor consumes #times."""
        with self._lock:
            f = self._faults.get(site)
            if f is None:
                return None
            if f.match and f.match not in key:
                return None
            if f.mode in ("rst", "partition") and forwarded < f.arg:
                return None
            f.hits += 1
            if f.times is not None:
                f.times -= 1
                if f.times <= 0:
                    del self._faults[site]
            return f.mode, f.arg

    # -- forwarding --------------------------------------------------------

    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                client, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            key = f"{addr[0]}:{addr[1]}"
            with self._lock:
                self.connections += 1
            hit = self._fault("connect", key)
            if hit is not None:
                mode, _arg = hit
                if mode in ("partition", "rst"):
                    with self._lock:
                        self.refused += 1
                    _hard_close(client)
                    continue
                if mode == "blackhole":
                    # accepted, never serviced: the client blocks on its
                    # own socket timeout (the half-open peer)
                    with self._lock:
                        self.refused += 1
                        self._conns.add((client, client))
                    continue
                if mode == "latency":
                    time.sleep(_arg)
                # throttle on connect is meaningless: ignore
            try:
                upstream = socket.create_connection(self.target,
                                                    timeout=10)
            except OSError:
                _hard_close(client)
                continue
            pair = (client, upstream)
            with self._lock:
                self._conns.add(pair)
            for site, src, dst in (("up", client, upstream),
                                   ("down", upstream, client)):
                t = threading.Thread(target=self._pump,
                                     args=(site, key, src, dst, pair),
                                     daemon=True)
                t.start()

    def _pump(self, site: str, key: str, src, dst, pair):
        forwarded = 0
        try:
            while not self._closing.is_set():
                try:
                    chunk = src.recv(CHUNK)
                except OSError:
                    break
                if not chunk:
                    break
                hit = self._fault(site, key, forwarded)
                if hit is not None:
                    mode, arg = hit
                    if mode == "latency":
                        time.sleep(arg)
                    elif mode == "throttle" and arg > 0:
                        time.sleep(len(chunk) / arg)
                    elif mode in ("rst", "partition"):
                        with self._lock:
                            self.rsts += 1
                        _hard_close(src)
                        _hard_close(dst)
                        break
                    elif mode == "blackhole":
                        with self._lock:
                            self.dropped_bytes += len(chunk)
                        continue  # swallowed: half-open drop
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
                forwarded += len(chunk)
                with self._lock:
                    if site == "up":
                        self.bytes_up += len(chunk)
                    else:
                        self.bytes_down += len(chunk)
        finally:
            # one direction ending ends the conversation: HTTP keep-alive
            # can't survive a half-dead tunnel, and the cluster client
            # re-dials transparently
            for s in pair:
                try:
                    s.close()
                except OSError:
                    pass
            with self._lock:
                self._conns.discard(pair)
