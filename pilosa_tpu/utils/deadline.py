"""End-to-end query deadlines (reference executor.go:2455 mapReduce
carrying ctx into every remoteExec hop).

A ``QueryContext`` is created at the HTTP edge (``?timeout=`` query
parameter, the ``X-Pilosa-Tpu-Deadline`` header on internal hops, or the
server's configured ``query-timeout`` default) and threaded through
``api.query`` -> ``Cluster.execute`` / ``Executor.execute`` -> the mesh
shard-slice loops.  Long-running phases call ``check()`` between units of
work (per PQL call, per shard slice, per fan-out retry wave) so an
expired query aborts instead of running to completion; the handler maps
``DeadlineExceeded`` to HTTP 504 with elapsed/budget in the body.

Across the wire the coordinator sends its REMAINING budget in the
``X-Pilosa-Tpu-Deadline`` header, so remotes inherit the shrunken budget
rather than restarting the clock (client-side socket timeouts are clamped
to the same remaining budget, bounding the total latency to ~the budget
even against a hung peer).

The active context also rides a contextvar so deep layers (mesh slice
iteration) can check it without threading a parameter through every
dispatch signature; worker threads that cross a pool boundary receive the
budget explicitly (the fan-out passes remaining seconds as an argument).
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager

# Remaining-budget header on node-to-node hops (seconds, float text).
DEADLINE_HEADER = "X-Pilosa-Tpu-Deadline"


class DeadlineExceeded(Exception):
    """The query ran past its deadline or was cancelled (HTTP 504)."""


class QueryContext:
    """Deadline + cancellation flag for one query's lifetime."""

    __slots__ = ("budget", "start", "deadline", "cancelled")

    def __init__(self, budget: float | None = None):
        self.budget = budget if budget and budget > 0 else None
        self.start = time.monotonic()
        self.deadline = None if self.budget is None \
            else self.start + self.budget
        self.cancelled = False

    def elapsed(self) -> float:
        return time.monotonic() - self.start

    def remaining(self) -> float | None:
        """Seconds left in the budget; None = unlimited."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        if self.cancelled:
            return True
        return self.deadline is not None \
            and time.monotonic() >= self.deadline

    def cancel(self):
        """Mark the query cancelled; the next check() aborts it."""
        self.cancelled = True

    def check(self, where: str = ""):
        """Raise DeadlineExceeded if expired/cancelled; no-op otherwise."""
        if not self.expired():
            return
        what = "query cancelled" if self.cancelled \
            else "query deadline exceeded"
        at = f" at {where}" if where else ""
        budget = f"{self.budget:.3f}s" if self.budget is not None else "-"
        raise DeadlineExceeded(
            f"{what}{at} (elapsed {self.elapsed():.3f}s, budget {budget})")


_CURRENT: contextvars.ContextVar[QueryContext | None] = \
    contextvars.ContextVar("pilosa_tpu_query_ctx", default=None)


def current() -> QueryContext | None:
    """The active QueryContext of this thread of execution, if any."""
    return _CURRENT.get()


@contextmanager
def activate(ctx: QueryContext | None):
    """Install ``ctx`` as the current context for the with-block.
    ``activate(None)`` is a no-op passthrough (keeps call sites simple)."""
    if ctx is None:
        yield None
        return
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


def check_current(where: str = ""):
    """check() on the current context; no-op when none is active."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.check(where)
