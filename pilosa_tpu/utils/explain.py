"""Query EXPLAIN: the per-query decision record
(docs/observability.md "Query EXPLAIN").

``?profile=true`` answers *where the time went*; ``?explain=true``
answers *why the query took the path it did*: how the request lowered
(whole-query program signature, or the counted fallback reason), which
replica each shard was routed to and what score chose it (EWMA RTT x
queue pressure x residency tier, breaker pre-skips), what the caches
decided (result-cache key components and hit/miss, rank-cache prune vs
full-scan fallback), which hedges fired and which won, and what the
device actually launched (signature, padded vs actual rows, decode
bytes).

All of that is telemetry the layers already compute at decision time —
this module is the contextvar spine that collects it, exactly the
``utils/profile.py`` pattern: the HTTP handler activates a record for
``?explain=true`` (and silently whenever the slow-query log is on, so
slow entries carry the record), deep layers append via module-level
``note()``/``set_info()`` (one contextvar read when inactive), and the
response embeds ``explain`` ONLY when requested.  Answers are
byte-identical with explain on — the record rides the response
envelope, never the results.

The launches section is assembled from the profile tree's
``device.launch``/``batcher.launch`` events rather than re-collected
(explain activation implies profile collection), so one launch has one
source of truth and the explain record cross-checks against the launch
ledger by signature."""

from __future__ import annotations

import contextvars
from contextlib import contextmanager

from .locks import make_lock

# Per-section entry cap: a 10k-shard fan-out must not build a 10k-row
# routing table into every slow-log entry.  Overflow is counted in the
# section's `truncated` field, never silently dropped.
SECTION_MAX = 256


class ExplainRecord:
    """One query's decision record.  Sections are append-only lists
    (routing / dispatch / caches / hedges / plan); ``info`` holds
    scalars.  Appends may come from any thread that inherited the
    request's context (the fan-out pool workers do, via Tracer.task's
    contextvar propagation)."""

    def __init__(self):
        self._lock = make_lock("explain")
        self._sections: dict[str, list] = {}
        self._truncated: dict[str, int] = {}
        self.info: dict = {}

    def note(self, section: str, entry: dict):
        with self._lock:
            rows = self._sections.setdefault(section, [])
            if len(rows) >= SECTION_MAX:
                self._truncated[section] = \
                    self._truncated.get(section, 0) + 1
                return
            rows.append(entry)

    def set_info(self, key: str, value):
        with self._lock:
            self.info[key] = value

    def to_dict(self, profile: dict | None = None) -> dict:
        with self._lock:
            out = dict(self.info)
            for section, rows in self._sections.items():
                out[section] = list(rows)
            for section, n in self._truncated.items():
                out.setdefault("truncated", {})[section] = n
        if profile is not None:
            launches = []
            _collect_launches(profile, launches)
            if launches:
                out["launches"] = launches[:SECTION_MAX]
        return out


def _collect_launches(node: dict, out: list):
    """Walk a profile tree for device-launch evidence: ``device.launch``
    events carry the executable signature + padded-vs-actual rows +
    decode bytes; ``batcher.launch`` events carry the fused-batch
    attribution for launches that ran on the dispatcher thread."""
    name = node.get("name")
    if name in ("device.launch", "batcher.launch"):
        entry = {"stage": name,
                 "durationMS": node.get("durationMS")}
        entry.update(node.get("tags") or {})
        out.append(entry)
    for c in node.get("children", ()):
        _collect_launches(c, out)


_VAR: contextvars.ContextVar[ExplainRecord | None] = \
    contextvars.ContextVar("pilosa_tpu_explain", default=None)


def current() -> ExplainRecord | None:
    return _VAR.get()


def active() -> bool:
    """Cheap gate for call sites whose entry CONSTRUCTION is the cost
    (the router's per-shard score table)."""
    return _VAR.get() is not None


def wants(section: str) -> bool:
    """True when a record is active AND ``section`` still has capacity.
    Hot call sites that build per-item entries in a loop (the router's
    per-shard score table on a many-thousand-shard fan-out) gate each
    iteration on this, so the SECTION_MAX cap bounds construction, not
    just storage — without it the overflow entries are built, locked,
    and then dropped."""
    rec = _VAR.get()
    if rec is None:
        return False
    with rec._lock:
        return len(rec._sections.get(section, ())) < SECTION_MAX


@contextmanager
def activate(rec: ExplainRecord | None):
    """Install ``rec`` for the with-block; activate(None) is a no-op
    passthrough (the profile.activate convention)."""
    if rec is None:
        yield None
        return
    token = _VAR.set(rec)
    try:
        yield rec
    finally:
        _VAR.reset(token)


def note(section: str, entry: dict):
    rec = _VAR.get()
    if rec is not None:
        rec.note(section, entry)


def set_info(key: str, value):
    rec = _VAR.get()
    if rec is not None:
        rec.set_info(key, value)
