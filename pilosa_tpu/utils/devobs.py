"""Device-runtime observability: compile/retrace telemetry + the launch
ledger (docs/observability.md "Device runtime").

The layers built since PR 5 — compressed residency, the decode
workspace, the dispatch batcher — run blind at the device-runtime level:
the PR 7 silent-retrace bug (a cached executable re-traced with another
group's container buckets, dropping run containers) produced zero signal
and was only caught by a bench differential.  This module is the signal:

* ``CompileRegistry`` (process-wide ``COMPILES``): every jit/shard_map
  executable boundary (parallel/mesh_exec.py, parallel/batcher.py's
  launches ride the same executables, the standalone decode buckets in
  ops/containers.py) notes each TRACE of its python body — jax only runs
  the body while tracing, so a ``mark_traced()`` call inside it is an
  exact compile detector.  Per signature: compile count, cumulative/last
  trace+compile wall time, and the argument-shape fingerprint of the
  last trace.  A signature compiling MORE than once is a retrace — a
  visible red flag (structured ``Logger.event`` with the fingerprint
  diff, a ``device.retrace`` span under the active trace, and the
  ``device.retraces_total`` gauge) instead of a silent wrong answer.

* ``LaunchLedger`` (process-wide ``LEDGER``): a bounded ring of recent
  device launches — signature, batch/group size, padded vs actual
  stacked rows (batcher padding waste becomes a measured ratio),
  decode-workspace bytes requested vs the ``decode-workspace-mb``
  ceiling, slice position, and the queue-vs-dispatch wall split — plus
  always-on launch/queue-wait histograms exported at /metrics
  (``pilosa_tpu_device_launch_seconds`` etc., the batcher-histogram
  pattern) and aggregates served at /debug/launches.

Timing discipline: every duration here comes from perf_counter pairs
taken by the instrumented call sites; ``_wall_stamp`` is display-only
correlation, never subtracted (scripts/check.sh lint).
"""

from __future__ import annotations

import contextvars
import hashlib
import threading
import time
from collections import OrderedDict, deque

from .locks import make_lock
from .stats import BucketHistogram


def _wall_stamp() -> float: return time.time()  # display-only wall clock


def fingerprint(args) -> str:
    """Compact argument-shape fingerprint of one executable call —
    ``8x4:int32|16x12x32768:uint32|...`` — the thing a retrace DIFFS:
    the PR 7 bug was exactly a shape change (stacked group size) hitting
    a cached executable."""
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is None:
            parts.append(type(a).__name__)
        else:
            parts.append("x".join(str(d) for d in shape) + ":"
                         + str(getattr(a, "dtype", "?")))
    return "|".join(parts)


def sig_of(key) -> str:
    """Stable short id for an executable cache key (the mesh plan key is
    a long tuple embedding plan reprs): ``<kind>:<10-hex-digest>``."""
    kind = key[0] if isinstance(key, tuple) and key else "exec"
    digest = hashlib.sha1(repr(key).encode()).hexdigest()[:10]
    return f"{kind}:{digest}"


class CompileRegistry:
    """Per-executable-signature compile/retrace telemetry.

    Call protocol (see mesh_exec._InstrumentedExec): ``begin_call()``
    clears this thread's trace flag, the traced python body calls
    ``mark_traced()``, and ``note_call()`` folds the finished call into
    the signature's entry when (and only when) the flag fired.  Tracing
    is synchronous on the calling thread, so a thread-local flag is
    exact even with concurrent launches."""

    MAX_ENTRIES = 512  # bounds /debug/compiles (LRU on compile recency)

    def __init__(self):
        self._lock = make_lock("compile-registry")
        self._local = threading.local()
        self._entries: OrderedDict[str, dict] = OrderedDict()
        self.compiles_total = 0
        self.retraces_total = 0
        self.compile_seconds_total = 0.0
        # Server injects its Logger so retraces land in the server log;
        # None (engine/bench standalone) keeps the registry silent.
        self.logger = None

    # -- trace detection (thread-local; tracing is synchronous) ------------

    def begin_call(self):
        self._local.traced = False

    def mark_traced(self):
        self._local.traced = True

    def traced(self) -> bool:
        return getattr(self._local, "traced", False)

    # -- recording ---------------------------------------------------------

    def note_call(self, sig: str, kind: str, dur_s: float, fp: str,
                  detail: str = "") -> bool:
        """Fold one finished executable call that TRACED (the caller
        checks ``traced()`` first — fingerprinting is only paid on
        compiles).  Returns True when this was a RETRACE (the signature
        had compiled before)."""
        retrace = None
        with self._lock:
            e = self._entries.get(sig)
            if e is None:
                while len(self._entries) >= self.MAX_ENTRIES:
                    self._entries.popitem(last=False)
                e = {"sig": sig, "kind": kind, "detail": detail,
                     "compiles": 0, "totalCompileS": 0.0,
                     "lastCompileS": 0.0, "lastFingerprint": "",
                     "lastCompileWall": 0.0}
                self._entries[sig] = e
            else:
                self._entries.move_to_end(sig)
            prev_fp = e["lastFingerprint"]
            e["compiles"] += 1
            e["totalCompileS"] += dur_s
            e["lastCompileS"] = dur_s
            e["lastFingerprint"] = fp
            e["lastCompileWall"] = _wall_stamp()
            self.compiles_total += 1
            self.compile_seconds_total += dur_s
            if e["compiles"] > 1:
                self.retraces_total += 1
                retrace = (prev_fp, e["compiles"])
        if retrace is None:
            return False
        prev_fp, n = retrace
        # journal the retrace (docs/observability.md "Cluster plane"):
        # the fleet timeline is where a retrace burst correlates with
        # the p99 spike it caused; emit() never raises
        from . import events
        events.emit("device.retrace", sig=sig, kind=kind, compiles=n,
                    shapes=fp)
        # Telemetry sinks must never take the query path down: the
        # injected logger outlives its Server (process-global registry,
        # most-recent-Server-wins), so a stale/closed stream is a lost
        # log line, not a failed dispatch.
        log = self.logger
        if log is not None:
            try:
                # the signature diff IS the diagnosis: what shape change
                # hit a cached executable (PR 7's was the stacked group
                # size)
                log.event("device.retrace", sig=sig, kind=kind,
                          compiles=n, compileS=round(dur_s, 4),
                          prevShapes=prev_fp, shapes=fp)
            # lint: allow(swallowed-exception) — a stale/closed log
            # stream costs a log line, never the dispatch; the retrace
            # is still counted in the compile registry above
            except Exception:
                pass
        try:
            from .tracing import GLOBAL_TRACER
            ctx = GLOBAL_TRACER.current()
            if ctx is not None and ctx.sampled:
                GLOBAL_TRACER.record_span(
                    "device.retrace", ctx.trace_id, ctx.span_id, dur_s,
                    {"sig": sig, "kind": kind, "compiles": n,
                     "prevShapes": prev_fp, "shapes": fp},
                    collect=ctx.collect)
        # lint: allow(swallowed-exception) — span synthesis is best-
        # effort decoration; the registry + log line above already
        # recorded the retrace, and tracing must never fail a dispatch
        except Exception:
            pass
        return True

    # -- surfaces ----------------------------------------------------------

    def totals(self) -> dict:
        with self._lock:
            return {"compiles": self.compiles_total,
                    "retraces": self.retraces_total,
                    "compileSecondsTotal": round(
                        self.compile_seconds_total, 4),
                    "executables": len(self._entries)}

    def snapshot(self) -> dict:
        """/debug/compiles: totals + per-signature entries, most recent
        compile last."""
        with self._lock:
            entries = [dict(e) for e in self._entries.values()]
        out = self.totals()
        out["entries"] = entries
        return out


# -- launch context (batcher -> ledger) -------------------------------------
# The dispatcher thread knows the queue wait and ticket count of the
# launch it is about to make; the instrumented executable it calls into
# reads them here.  A contextvar (not a plain thread-local) so the value
# also survives any context-propagating hop in between.

_LAUNCH_CTX: contextvars.ContextVar[dict | None] = \
    contextvars.ContextVar("pilosa_tpu_launch_ctx", default=None)
# Streaming slice position, set by mesh_exec._ShardSchedule around each
# yielded slice: (slice_index, slice_count).
_SLICE: contextvars.ContextVar[tuple | None] = \
    contextvars.ContextVar("pilosa_tpu_launch_slice", default=None)


def set_launch_ctx(queue_s: float = 0.0, tickets: int = 1,
                   rows: int | None = None):
    """Annotate subsequent launches on this thread of execution (the
    batcher's dispatcher sets it per launch); returns a reset token."""
    return _LAUNCH_CTX.set(
        {"queue_s": queue_s, "tickets": tickets, "rows": rows})


def reset_launch_ctx(token):
    _LAUNCH_CTX.reset(token)


def launch_ctx() -> dict | None:
    return _LAUNCH_CTX.get()


def set_slice(idx: int | None, count: int | None = None):
    _SLICE.set(None if idx is None else (idx, count))


def current_slice() -> tuple | None:
    return _SLICE.get()


class LaunchLedger:
    """Bounded ring of recent device launches + always-on aggregates.

    One entry per compiled-executable invocation (the mesh dispatch
    choke point): what launched, how padded, how much transient decode
    workspace it asked for, and how long it queued vs dispatched.
    ``rows`` here are launch units — stacked shard rows x fused query
    rows — so both the shard-axis bucket padding and the batcher's
    pow-2 query-axis padding show up in one waste ratio."""

    def __init__(self, size: int = 256):
        self._lock = make_lock("launch-ledger")
        self.size = max(int(size), 1)
        self._ring: deque = deque(maxlen=self.size)
        self.launches_total = 0
        self.rows_actual_total = 0
        self.rows_padded_total = 0
        self.decode_peak_bytes = 0   # high-watermark of per-launch decode
        self.decode_bytes_total = 0
        # Pallas container-kernel accounting (ops/kernels.py): launches
        # that embedded fused decode kernels, and the VMEM container
        # tiles those kernels walked — decode bytes measured as tile
        # traffic instead of an XLA temp watermark
        self.kernel_launches_total = 0
        self.kernel_tiles_total = 0
        # exported as pilosa_tpu_device_* histogram families at /metrics
        # (own exposition like the batcher's, outside the stats client)
        self.launch_hist = BucketHistogram(
            [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0])
        self.queue_hist = BucketHistogram(
            [0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05,
             0.1, 0.5])

    def resize(self, size: int):
        """Apply launch-ledger-size (most recent Server's config wins,
        like the memory budgets); keeps the newest entries."""
        size = max(int(size), 1)
        with self._lock:
            if size != self.size:
                self._ring = deque(self._ring, maxlen=size)
                self.size = size

    def record(self, *, sig: str, kind: str, shards: int,
               shards_padded: int, batch_rows: int,
               batch_rows_padded: int, queue_s: float, dispatch_s: float,
               decode_bytes: int, compiled: bool, tickets: int = 1,
               slice_pos: tuple | None = None, kernel_launches: int = 0,
               kernel_tiles: int = 0):
        actual = max(shards, 0) * max(batch_rows, 1)
        total = max(shards_padded, shards) * max(batch_rows_padded,
                                                 batch_rows, 1)
        padded = max(total - actual, 0)
        entry = {
            "wall": _wall_stamp(), "sig": sig, "kind": kind,
            "shards": shards, "shardsPadded": shards_padded,
            "batchRows": batch_rows, "batchRowsPadded": batch_rows_padded,
            "rowsActual": actual, "rowsPadded": padded,
            "queueS": round(queue_s, 6), "dispatchS": round(dispatch_s, 6),
            "decodeBytes": decode_bytes, "compiled": compiled,
            "tickets": tickets,
        }
        if slice_pos is not None:
            entry["slice"] = slice_pos[0]
            entry["slices"] = slice_pos[1]
        if kernel_launches:
            entry["kernelLaunches"] = kernel_launches
            entry["kernelTiles"] = kernel_tiles
        with self._lock:
            self._ring.append(entry)
            self.launches_total += 1
            self.rows_actual_total += actual
            self.rows_padded_total += padded
            self.decode_bytes_total += decode_bytes
            self.decode_peak_bytes = max(self.decode_peak_bytes,
                                         decode_bytes)
            self.kernel_launches_total += kernel_launches
            self.kernel_tiles_total += kernel_tiles
        self.launch_hist.observe(dispatch_s)
        if queue_s > 0:
            self.queue_hist.observe(queue_s)

    def reset_decode_peak(self):
        """Restart the decode-workspace high-watermark (bench-leg
        brackets — the gauge analog of DeviceBudget.reset_peak), so each
        leg reports its own peak instead of a predecessor's."""
        with self._lock:
            self.decode_peak_bytes = 0

    def padding_waste_ratio(self) -> float:
        with self._lock:
            total = self.rows_actual_total + self.rows_padded_total
            return self.rows_padded_total / total if total else 0.0

    def aggregates(self) -> dict:
        with self._lock:
            total = self.rows_actual_total + self.rows_padded_total
            return {
                "launches": self.launches_total,
                "rowsActual": self.rows_actual_total,
                "rowsPadded": self.rows_padded_total,
                "paddingWasteRatio": round(
                    self.rows_padded_total / total, 4) if total else 0.0,
                "decodePeakBytes": self.decode_peak_bytes,
                "decodeBytesTotal": self.decode_bytes_total,
                "kernelLaunches": self.kernel_launches_total,
                "kernelTiles": self.kernel_tiles_total,
                "size": self.size,
            }

    def snapshot(self) -> dict:
        """/debug/launches: aggregates + the ring, newest last."""
        out = self.aggregates()
        with self._lock:
            out["entries"] = list(self._ring)
        out["launchS"] = self.launch_hist.snapshot()
        out["queueS"] = self.queue_hist.snapshot()
        return out

    def prometheus_text(self) -> str:
        lines = self.launch_hist.prometheus_lines(
            "pilosa_tpu_device_launch_seconds")
        lines += self.queue_hist.prometheus_lines(
            "pilosa_tpu_device_launch_queue_seconds")
        return "\n".join(lines) + "\n"


# Process-wide singletons, like DEFAULT_BUDGET: one device runtime per
# process, one telemetry surface.  Tests use deltas or private instances.
COMPILES = CompileRegistry()
LEDGER = LaunchLedger()
