"""Deterministic failpoint registry for chaos/robustness testing.

Production behavior is a no-op: ``FAULTS.hit(name)`` returns after one
empty-dict check when nothing is armed.  Tests (and operators running
game-days) arm failpoints programmatically (``FAULTS.arm``) or via the
``failpoints`` config knob / ``PILOSA_TPU_FAILPOINTS`` env var, using a
compact spec:

    name=mode[:arg][@match][#times][;name=...]

    client.request=error@localhost:10102        every request to that host
                                                fails as a transport error
    mesh.slice=delay:0.25@myindex#3             first three shard slices of
                                                queries over 'myindex' sleep
                                                250 ms before dispatch
    fragment.snapshot=error                     snapshot writes fail

Modes: ``error`` raises ``FaultInjected`` (an OSError subclass, so
transport-level handling — client retries, circuit breakers, fan-out
replica retry — exercises its real error paths), ``delay:<seconds>``
sleeps, and ``kill[:skip]`` SIGKILLs the OWN process after skipping the
first ``skip`` hits — the crash harness's way of dying at an exact
byte-level failpoint (mid snapshot rename, between WAL frame appends)
instead of at a random wall-clock instant.  ``@match`` is a substring
filter on the key the hit site passes (host+path for client requests,
index name for mesh slices, file path for storage); ``#times`` disarms
after that many triggers.

Woven into: ``InternalClient._request`` (client.request), fragment
snapshot/WAL writes (fragment.snapshot / fragment.wal), and the mesh
shard-slice loop (mesh.slice) — every overload/failure path is testable
without real partitions (the failpoints.go idea from the reference's
test suite, env-armed).
"""

from __future__ import annotations

import time

from .locks import make_lock


class FaultInjected(OSError):
    """Injected failure.  An OSError so transport/storage error handling
    treats it exactly like the real fault it simulates."""


def parse_spec(spec: str) -> list[tuple[str, str, float, str | None,
                                        int | None]]:
    """Parse a ``name=mode[:arg][@match][#times];...`` spec into
    ``(name, mode, arg, match, times)`` tuples.  Shared grammar between
    the in-process failpoint registry (this module) and the network
    fault layer (utils/netchaos.py ChaosProxy) — one spec syntax for
    every chaos surface; each consumer validates its own mode set."""
    out = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, rhs = part.partition("=")
        if not rhs:
            raise ValueError(f"bad failpoint spec {part!r}")
        times = None
        if "#" in rhs:
            rhs, _, t = rhs.rpartition("#")
            times = int(t)
        match = None
        if "@" in rhs:
            rhs, _, match = rhs.partition("@")
        mode, _, arg = rhs.partition(":")
        out.append((name.strip(), mode.strip(),
                    float(arg) if arg else 0.0, match or None, times))
    return out


class _Fault:
    __slots__ = ("mode", "arg", "match", "times", "hits")

    def __init__(self, mode: str, arg: float, match: str | None,
                 times: int | None):
        self.mode = mode
        self.arg = arg
        self.match = match
        self.times = times
        self.hits = 0


class FaultRegistry:
    def __init__(self):
        self._faults: dict[str, _Fault] = {}
        self._lock = make_lock("faults")

    def arm(self, name: str, mode: str = "error", arg: float = 0.0,
            match: str | None = None, times: int | None = None):
        if mode not in ("error", "delay", "kill"):
            raise ValueError(f"unknown failpoint mode {mode!r}")
        with self._lock:
            self._faults[name] = _Fault(mode, arg, match, times)

    def disarm(self, name: str | None = None):
        with self._lock:
            if name is None:
                self._faults.clear()
            else:
                self._faults.pop(name, None)

    def configure(self, spec: str):
        """Parse and arm a ``name=mode[:arg][@match][#times];...`` spec."""
        for name, mode, arg, match, times in parse_spec(spec):
            self.arm(name, mode, arg, match, times)

    def hit(self, name: str, key: str = ""):
        """Trigger point.  MUST stay near-free when nothing is armed —
        it sits on hot paths (WAL appends, slice dispatch)."""
        if not self._faults:          # production fast path, no lock
            return
        with self._lock:
            f = self._faults.get(name)
            if f is None:
                return
            if f.match and f.match not in key:
                return
            f.hits += 1
            if f.mode == "kill" and f.arg > 0:
                # kill:skip — let the first `skip` hits through so the
                # crash harness can die on a RANDOM later occurrence of
                # the same failpoint, not always the first
                f.arg -= 1
                return
            if f.times is not None:
                f.times -= 1
                if f.times <= 0:
                    del self._faults[name]
            mode, arg = f.mode, f.arg
        if mode == "delay":
            time.sleep(arg)
        elif mode == "kill":
            # kill -9 the OWN process at this exact failpoint: no atexit,
            # no flushing, no destructors — the crash the durability
            # contract is written against (docs/robustness.md)
            import os
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        else:
            raise FaultInjected(f"failpoint {name!r} injected (key={key!r})")

    def snapshot(self) -> dict:
        with self._lock:
            return {name: {"mode": f.mode, "arg": f.arg, "match": f.match,
                           "timesLeft": f.times, "hits": f.hits}
                    for name, f in self._faults.items()}


FAULTS = FaultRegistry()
