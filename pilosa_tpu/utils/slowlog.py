"""Slow-query log: a bounded ring of queries that ran past
``slow-query-threshold``, each entry carrying the query text, index,
shard count, trace id, final status, and the per-query profile tree —
exposed at ``GET /debug/slow`` and emitted as structured log lines with
trace correlation (docs/observability.md).

The ring is in-process and fixed-size (``slow-log-size``): recording is
O(1) and the memory bound is entries x truncated-query-size, so an
always-on threshold cannot grow the heap.  Health/status probes are
tagged at the HTTP edge and never reach record()."""

from __future__ import annotations

import time
from collections import deque

from .locks import make_lock

# Default ceiling on query text stored per entry: the log must bound
# memory even against megabyte PQL bodies.  Per-instance override via
# the ``slow-log-text-max`` knob (a recorded-workload replay harness
# wants entries it can replay VERBATIM, so it raises the ceiling and
# skips the ones still marked ``textTruncated`` — bench.py's harvest).
QUERY_TEXT_MAX = 512


class SlowQueryLog:
    def __init__(self, threshold_s: float = 1.0, size: int = 128,
                 logger=None, stats=None, text_max: int | None = None):
        self.threshold_s = threshold_s
        self.size = max(int(size), 1)
        # `is not None`, not truthiness: an explicit 0 means "store no
        # query text" (e.g. sensitive PQL bodies), not "use the default"
        self.text_max = int(text_max) if text_max is not None \
            else QUERY_TEXT_MAX
        self.logger = logger
        self.stats = stats
        self._entries: deque = deque(maxlen=self.size)
        self._lock = make_lock("slowlog")
        self.recorded = 0

    @property
    def enabled(self) -> bool:
        return self.threshold_s > 0

    def record(self, *, index: str, query: str, duration_s: float,
               shards: int | None = None, trace_id: str | None = None,
               status: int = 200, profile: dict | None = None,
               explain: dict | None = None):
        full_len = len(query or "")
        query = (query or "")[:self.text_max]
        entry = {
            # wall stamp for operator correlation only; the duration was
            # measured by the caller from a perf_counter pair
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "durationS": round(duration_s, 4),
            "index": index,
            "query": query,
            "shards": shards,
            "traceID": trace_id,
            "status": status,
        }
        if full_len > len(query):
            # an explicit flag, not a length heuristic: replay tooling
            # must KNOW the text is partial (a truncated batch replays
            # as a parse error — the PR 13 harvest bug)
            entry["textTruncated"] = True
        if profile is not None:
            entry["profile"] = profile
        if explain is not None:
            entry["explain"] = explain
        with self._lock:
            self._entries.append(entry)
            self.recorded += 1
        if self.stats is not None:
            self.stats.count("slowlog.recorded")
        if self.logger is not None:
            # structured line with trace correlation (utils/logger.py):
            # `trace=<id>` joins the log stream to /debug/traces
            emit = getattr(self.logger, "event", None)
            if emit is not None:
                emit("slow-query", durationS=entry["durationS"],
                     index=index, shards=shards, status=status,
                     trace=trace_id, query=query)
            else:
                self.logger.info(
                    f"slow-query durationS={entry['durationS']} "
                    f"index={index} shards={shards} status={status} "
                    f"trace={trace_id} query={query!r}")

    def snapshot(self) -> dict:
        with self._lock:
            entries = list(self._entries)
        return {
            "thresholdS": self.threshold_s,
            "size": self.size,
            "textMax": self.text_max,
            "recorded": self.recorded,
            "entries": entries,
        }

    def clear(self):
        with self._lock:
            self._entries.clear()
