"""GC-cycle notification (reference gcnotify/gcnotify.go).

The reference registers for Go GC cycle notifications so long-running
maintenance (anti-entropy) can observe collector pressure.  CPython's
collector is a different beast (refcounting + generational cycle
collector), but the observable the row asks for is the same: per-cycle
counts and stop-the-world pause time.  ``gc.callbacks`` delivers
start/stop around every cyclic collection; this module aggregates them
into per-generation counters surfaced as ``runtime.gc_*`` gauges
(server.collect_runtime_stats) and /metrics.
"""

from __future__ import annotations

import gc
import time

from .locks import make_lock


class GcNotifier:
    """Aggregates gc.callbacks events; safe to create/close repeatedly."""

    def __init__(self):
        self._lock = make_lock("gcnotify")
        self.collections = [0, 0, 0]
        self.pause_s = [0.0, 0.0, 0.0]
        self.collected = 0   # objects reclaimed by the cycle collector
        self.uncollectable = 0
        self._t0 = None
        gc.callbacks.append(self._callback)

    def _callback(self, phase, info):
        gen = min(int(info.get("generation", 0)), 2)
        if phase == "start":
            self._t0 = time.perf_counter()
            return
        dt = 0.0 if self._t0 is None else time.perf_counter() - self._t0
        self._t0 = None
        with self._lock:
            self.collections[gen] += 1
            self.pause_s[gen] += dt
            self.collected += int(info.get("collected", 0))
            self.uncollectable += int(info.get("uncollectable", 0))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "collections": list(self.collections),
                "pause_s": list(self.pause_s),
                "collected": self.collected,
                "uncollectable": self.uncollectable,
            }

    def close(self):
        try:
            gc.callbacks.remove(self._callback)
        except ValueError:
            pass


_global = None
_global_lock = make_lock("gcnotify-global")


def global_notifier() -> GcNotifier:
    global _global
    with _global_lock:
        if _global is None:
            _global = GcNotifier()
        return _global
