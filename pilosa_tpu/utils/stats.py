"""Stats client (reference stats/stats.go:31-161 StatsClient iface).

In-process counters/gauges/timings with tag support; snapshot() feeds both
the expvar-style /debug/vars JSON and the Prometheus text exposition at
/metrics (reference prometheus/prometheus.go)."""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class StatsClient:
    def __init__(self, tags: list[str] | None = None):
        self.tags = tags or []
        self._lock = threading.Lock()
        self._counts: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._timings: dict[str, list[float]] = defaultdict(list)

    def with_tags(self, *tags: str) -> "StatsClient":
        child = StatsClient(self.tags + list(tags))
        child._lock = self._lock  # shared metrics need the shared lock
        child._counts = self._counts
        child._gauges = self._gauges
        child._timings = self._timings
        return child

    def _key(self, name: str) -> str:
        if not self.tags:
            return name
        return name + "{" + ",".join(sorted(self.tags)) + "}"

    def count(self, name: str, value: float = 1, rate: float = 1.0):
        with self._lock:
            self._counts[self._key(name)] += value

    def gauge(self, name: str, value: float, rate: float = 1.0):
        with self._lock:
            self._gauges[self._key(name)] = value

    def timing(self, name: str, value_s: float, rate: float = 1.0):
        with self._lock:
            self._timings[self._key(name)].append(value_s)

    def histogram(self, name: str, value: float, rate: float = 1.0):
        self.timing(name, value, rate)

    def set_value(self, name: str, value: str, rate: float = 1.0):
        with self._lock:
            self._gauges[self._key(name) + ":" + value] = 1

    class _Timer:
        def __init__(self, client, name):
            self.client, self.name = client, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.client.timing(self.name, time.perf_counter() - self.t0)

    def timer(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            timings = {
                k: {"count": len(v), "sum": sum(v),
                    "mean": sum(v) / len(v) if v else 0}
                for k, v in self._timings.items()
            }
            return {"counts": dict(self._counts),
                    "gauges": dict(self._gauges),
                    "timings": timings}

    def prometheus_text(self) -> str:
        """Prometheus exposition format for /metrics
        (prometheus/prometheus.go:40)."""
        lines = []

        def fmt(name):
            base, _, tags = name.partition("{")
            base = "pilosa_tpu_" + base.replace(".", "_").replace("-", "_")
            return base + ("{" + tags if tags else "")

        snap = self.snapshot()
        for k, v in sorted(snap["counts"].items()):
            lines.append(f"# TYPE {fmt(k).split('{')[0]} counter")
            lines.append(f"{fmt(k)} {v}")
        for k, v in sorted(snap["gauges"].items()):
            lines.append(f"# TYPE {fmt(k).split('{')[0]} gauge")
            lines.append(f"{fmt(k)} {v}")
        for k, t in sorted(snap["timings"].items()):
            base = fmt(k).split("{")[0]
            lines.append(f"# TYPE {base}_seconds summary")
            lines.append(f"{base}_seconds_count {t['count']}")
            lines.append(f"{base}_seconds_sum {t['sum']}")
        return "\n".join(lines) + "\n"


class NopStatsClient(StatsClient):
    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass
