"""Stats client (reference stats/stats.go:31-161 StatsClient iface).

In-process counters/gauges/timings with tag support; snapshot() feeds both
the expvar-style /debug/vars JSON and the Prometheus text exposition at
/metrics (reference prometheus/prometheus.go).

Timings are fixed LOG-BUCKET histograms (docs/observability.md): O(1)
memory per series over a server's lifetime like the old [count, sum]
aggregation, but able to answer p50/p95/p99 (Monarch/Prometheus-style
bucketed latency distributions) and exported as proper Prometheus
``_bucket``/``_sum``/``_count`` histogram series at /metrics."""

from __future__ import annotations

import time
from collections import defaultdict

from .locks import make_lock

# Inclusive upper edges for timing histograms: 1-2.5-5 per decade from
# 100 µs to 100 s (values above land in +Inf).  Fixed and shared by every
# series so /metrics stays aggregatable across nodes.
TIMING_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)


class _Hist:
    """One timing series: count, sum, and per-bucket counters over the
    shared TIMING_BUCKETS edges.  Mutated under the owning client's
    lock.

    Each bucket also keeps its LAST trace-id exemplar (trace_id, value,
    wall) — O(buckets) memory, and exactly the link a p99 investigation
    needs: the `/metrics` exposition emits OpenMetrics-style exemplars
    on the bucket lines, so the trace id behind a latency spike resolves
    directly at ``/debug/traces?trace=<id>`` (docs/observability.md
    "Trace exemplars")."""

    __slots__ = ("count", "total", "buckets", "exemplars")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.buckets = [0] * (len(TIMING_BUCKETS) + 1)
        # per-bucket (trace_id, value, wall) of the last exemplar-tagged
        # observation that landed there; None until one does
        self.exemplars: list = [None] * (len(TIMING_BUCKETS) + 1)

    def observe(self, v: float, exemplar: str | None = None):
        self.count += 1
        self.total += v
        for i, b in enumerate(TIMING_BUCKETS):
            if v <= b:
                self.buckets[i] += 1
                if exemplar is not None:
                    # lint: allow(wall-clock) — exemplar timestamps are
                    # display-only correlation, never subtracted
                    self.exemplars[i] = (exemplar, v, time.time())
                return
        self.buckets[-1] += 1
        if exemplar is not None:
            # lint: allow(wall-clock) — display-only exemplar timestamp
            self.exemplars[-1] = (exemplar, v, time.time())

    def percentile(self, q: float) -> float | None:
        """Order statistic estimated from the buckets with linear
        interpolation inside the winning bucket (the histogram_quantile
        formula) — deterministic given the recorded values, so golden-
        value testable."""
        if self.count == 0:
            return None
        target = q * self.count
        cum = 0
        lo = 0.0
        for i, hi in enumerate(TIMING_BUCKETS):
            prev = cum
            cum += self.buckets[i]
            if cum >= target:
                if self.buckets[i] == 0:
                    return hi
                frac = (target - prev) / self.buckets[i]
                return lo + frac * (hi - lo)
            lo = hi
        return TIMING_BUCKETS[-1]  # +Inf bucket: clamp to the last edge


class StatsClient:
    # Distinct values tracked per set_value() name before further values
    # collapse into one ":__other__" series: set_value feeds gauges, and
    # an unbounded dynamic value (client-chosen strings) must not grow
    # the gauge map — and /metrics — without bound.
    SET_VALUE_CAP = 64

    def __init__(self, tags: list[str] | None = None):
        self.tags = tags or []
        self._lock = make_lock("stats")
        self._counts: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        # per-series log-bucket histograms — NOT raw samples: always-on
        # per-query timings must stay O(1) memory over a server's lifetime
        self._timings: dict[str, _Hist] = defaultdict(_Hist)
        # distinct values seen per set_value name (cardinality cap)
        self._set_values: dict[str, set] = defaultdict(set)

    def with_tags(self, *tags: str) -> "StatsClient":
        child = StatsClient(self.tags + list(tags))
        self._share_with(child)
        return child

    def _share_with(self, child: "StatsClient"):
        child._lock = self._lock  # shared metrics need the shared lock
        child._counts = self._counts
        child._gauges = self._gauges
        child._timings = self._timings
        child._set_values = self._set_values

    def _key(self, name: str) -> str:
        if not self.tags:
            return name
        return name + "{" + ",".join(sorted(self.tags)) + "}"

    def count(self, name: str, value: float = 1, rate: float = 1.0):
        with self._lock:
            self._counts[self._key(name)] += value

    def gauge(self, name: str, value: float, rate: float = 1.0):
        with self._lock:
            self._gauges[self._key(name)] = value

    def timing(self, name: str, value_s: float, rate: float = 1.0,
               exemplar: str | None = None):
        """``exemplar``: optional trace id attached to the bucket this
        observation lands in (only pass ids of SAMPLED traces — an
        exemplar must resolve at /debug/traces)."""
        with self._lock:
            self._timings[self._key(name)].observe(value_s, exemplar)

    def histogram(self, name: str, value: float, rate: float = 1.0):
        self.timing(name, value, rate)

    def percentile(self, name: str, q: float) -> float | None:
        """q-quantile (0..1) of a recorded timing/histogram series, or
        None when nothing has been recorded under ``name``."""
        with self._lock:
            h = self._timings.get(self._key(name))
            return None if h is None else h.percentile(q)

    def count_value(self, name: str) -> float:
        """One counter's current value without building the full
        snapshot — the time-series sampler reads a handful per tick
        (the timing_totals pattern)."""
        with self._lock:
            return self._counts.get(self._key(name), 0.0)

    def bucket_count_le(self, name: str, bound_s: float) -> int:
        """Observations of one timing series in buckets whose upper
        edge is <= ``bound_s`` — the SLO engine's good-count reader
        (utils/slo.py): exact when ``bound_s`` is a TIMING_BUCKETS
        edge, and conservatively snapped DOWN to the nearest edge
        otherwise (a query is never counted good on a bucket that may
        contain over-objective observations)."""
        with self._lock:
            h = self._timings.get(self._key(name))
            if h is None:
                return 0
            n = 0
            for edge, c in zip(TIMING_BUCKETS, h.buckets):
                if edge > bound_s:
                    break
                n += c
            return n

    def timing_totals(self, name: str) -> tuple[int, float]:
        """(count, sum) of one timing series without building the full
        snapshot — the time-series sampler reads these every interval,
        and interpolating every series' percentiles per tick would be
        pure waste."""
        with self._lock:
            h = self._timings.get(self._key(name))
            return (0, 0.0) if h is None else (h.count, h.total)

    def set_value(self, name: str, value: str, rate: float = 1.0):
        with self._lock:
            key = self._key(name)
            seen = self._set_values[key]
            if value not in seen:
                if len(seen) >= self.SET_VALUE_CAP:
                    value = "__other__"
                seen.add(value)
            self._gauges[key + ":" + value] = 1

    class _Timer:
        def __init__(self, client, name):
            self.client, self.name = client, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.client.timing(self.name, time.perf_counter() - self.t0)

    def timer(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            timings = {
                k: {"count": h.count, "sum": h.total,
                    "mean": h.total / h.count if h.count else 0,
                    "p50": h.percentile(0.50),
                    "p95": h.percentile(0.95),
                    "p99": h.percentile(0.99)}
                for k, h in self._timings.items()
            }
            return {"counts": dict(self._counts),
                    "gauges": dict(self._gauges),
                    "timings": timings}

    def prometheus_text(self, exemplars: bool = False) -> str:
        """Prometheus exposition format for /metrics
        (prometheus/prometheus.go:40).  Timings export as histogram
        families: cumulative ``_bucket{le=...}`` series over the shared
        TIMING_BUCKETS edges plus ``_sum``/``_count``, so p99 is
        derivable with histogram_quantile.

        ``exemplars=True`` appends the per-bucket trace-id exemplars in
        OpenMetrics syntax — legal ONLY on the negotiated OpenMetrics
        exposition (the classic 0.0.4 text parser rejects a ``# {...}``
        token after a sample value, which would black out the whole
        scrape); the handler sets it from the Accept header."""
        lines = []

        def fmt(name):
            base, _, tags = name.partition("{")
            base = "pilosa_tpu_" + base.replace(".", "_").replace("-", "_")
            return base + ("{" + tags if tags else "")

        snap = self.snapshot()
        with self._lock:
            hists = {k: (h.count, h.total, list(h.buckets),
                         list(h.exemplars))
                     for k, h in self._timings.items()}
        for k, v in sorted(snap["counts"].items()):
            lines.append(f"# TYPE {fmt(k).split('{')[0]} counter")
            lines.append(f"{fmt(k)} {v}")
        for k, v in sorted(snap["gauges"].items()):
            lines.append(f"# TYPE {fmt(k).split('{')[0]} gauge")
            lines.append(f"{fmt(k)} {v}")

        # bound before the histogram loop, whose per-series `exemplars`
        # variable shadows the parameter inside the closure
        with_exemplars = exemplars

        def exemplar_suffix(ex):
            # OpenMetrics exemplar syntax on the bucket the observation
            # landed in: `... # {trace_id="<id>"} <value> <timestamp>` —
            # the p99-spike -> /debug/traces link
            # (docs/observability.md "Trace exemplars")
            if ex is None or not with_exemplars:
                return ""
            tid, val, wall = ex
            return (f' # {{trace_id="{tid}"}} {round(val, 6)}'
                    f" {round(wall, 3)}")

        for k, (count, total, buckets, exemplars) in \
                sorted(hists.items()):
            full = fmt(k)
            base, _, tags = full.partition("{")
            tags = tags.rstrip("}")  # series tags, merged with le below
            prefix = ",".join(t for t in (tags,) if t)
            lines.append(f"# TYPE {base}_seconds histogram")
            cum = 0
            for i, (edge, c) in enumerate(zip(TIMING_BUCKETS, buckets)):
                cum += c
                lbl = f'{prefix},le="{edge}"' if prefix else f'le="{edge}"'
                lines.append(f"{base}_seconds_bucket{{{lbl}}} {cum}"
                             + exemplar_suffix(exemplars[i]))
            cum += buckets[-1]
            lbl = f'{prefix},le="+Inf"' if prefix else 'le="+Inf"'
            lines.append(f"{base}_seconds_bucket{{{lbl}}} {cum}"
                         + exemplar_suffix(exemplars[-1]))
            suffix = "{" + prefix + "}" if prefix else ""
            lines.append(f"{base}_seconds_sum{suffix} {total}")
            lines.append(f"{base}_seconds_count{suffix} {count}")
        return "\n".join(lines) + "\n"


class BucketHistogram:
    """Fixed-bucket counting histogram — bounded memory for always-on
    hot-path recording (the dispatch batcher's batch-size distribution).
    ``bounds`` are inclusive upper edges; values above the last bound land
    in the +Inf bucket."""

    def __init__(self, bounds):
        self.bounds = list(bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._lock = make_lock("stats")
        self.count = 0
        self.total = 0.0

    def observe(self, v: float):
        with self._lock:
            self.count += 1
            self.total += v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        with self._lock:
            out = {f"le_{b}": c for b, c in zip(self.bounds, self._counts)}
            out["le_inf"] = self._counts[-1]
            out["count"] = self.count
            out["sum"] = self.total
            return out

    def prometheus_lines(self, name: str) -> list[str]:
        """Cumulative-bucket exposition (Prometheus histogram type)."""
        with self._lock:
            lines = [f"# TYPE {name} histogram"]
            cum = 0
            for b, c in zip(self.bounds, self._counts):
                cum += c
                lines.append(f'{name}_bucket{{le="{b}"}} {cum}')
            cum += self._counts[-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{name}_sum {self.total}")
            lines.append(f"{name}_count {self.count}")
            return lines


class ReservoirTimer:
    """Ring buffer of the last ``size`` duration samples; percentile()
    computes order statistics over a snapshot copy.  O(size) memory over
    a server's lifetime, like the aggregated timings above — but able to
    answer p50/p99 (the window-wait distribution the batch dispatcher
    publishes)."""

    def __init__(self, size: int = 512):
        self.size = size
        self._buf: list[float] = []
        self._pos = 0
        self._lock = make_lock("stats")
        self.count = 0

    def observe(self, v: float):
        with self._lock:
            self.count += 1
            if len(self._buf) < self.size:
                self._buf.append(v)
            else:
                self._buf[self._pos] = v
                self._pos = (self._pos + 1) % self.size

    def percentile(self, q: float) -> float | None:
        with self._lock:
            buf = sorted(self._buf)
        if not buf:
            return None
        i = min(len(buf) - 1, int(q * (len(buf) - 1) + 0.5))
        return buf[i]

    def snapshot(self) -> dict:
        return {"count": self.count,
                "p50": self.percentile(0.5),
                "p99": self.percentile(0.99)}


class StatsdClient(StatsClient):
    """StatsClient that ALSO emits DataDog-flavored statsd UDP datagrams
    (reference statsd/statsd.go) while keeping the in-process snapshot so
    /debug/vars and /metrics stay live."""

    def __init__(self, host: str = "localhost", port: int = 8125,
                 tags: list[str] | None = None, sock=None):
        super().__init__(tags)
        import socket
        self._addr = (host, port)
        self._sock = sock if sock is not None else socket.socket(
            socket.AF_INET, socket.SOCK_DGRAM)

    def with_tags(self, *tags: str) -> "StatsdClient":
        child = StatsdClient(*self._addr, tags=self.tags + list(tags),
                             sock=self._sock)
        self._share_with(child)
        return child

    def _send(self, payload: str):
        if self.tags:
            payload += "|#" + ",".join(sorted(self.tags))
        try:
            self._sock.sendto(payload.encode(), self._addr)
        except OSError:
            pass  # stats must never take the server down (statsd.go:101)

    def count(self, name: str, value: float = 1, rate: float = 1.0):
        super().count(name, value, rate)
        self._send(f"{name}:{value}|c")

    def gauge(self, name: str, value: float, rate: float = 1.0):
        super().gauge(name, value, rate)
        self._send(f"{name}:{value}|g")

    def timing(self, name: str, value_s: float, rate: float = 1.0,
               exemplar: str | None = None):
        super().timing(name, value_s, rate, exemplar)
        self._send(f"{name}:{value_s * 1e3:.3f}|ms")

    def histogram(self, name: str, value: float, rate: float = 1.0):
        # record in-process via the BASE timing (bucketed, feeds
        # /metrics + percentile) but wire as a statsd histogram, not ms
        StatsClient.timing(self, name, value, rate)
        self._send(f"{name}:{value}|h")

    def set_value(self, name: str, value: str, rate: float = 1.0):
        super().set_value(name, value, rate)
        self._send(f"{name}:{value}|s")


def make_stats_client(service: str = "expvar", host: str = "localhost:8125"
                      ) -> StatsClient:
    """Backend selection by config (server/server.go:268): "expvar" (also
    serves "prometheus" — both read the in-process snapshot), "statsd", or
    "none"/"nop"."""
    if service == "statsd":
        if ":" in host:
            h, _, p = host.rpartition(":")
            return StatsdClient(h or "localhost", int(p))
        return StatsdClient(host or "localhost", 8125)
    if service in ("none", "nop"):
        return NopStatsClient()
    return StatsClient()


class NopStatsClient(StatsClient):
    """Discards everything but keeps the FULL StatsClient surface —
    histogram/percentile/set_value included — so a no-op-configured
    server never AttributeErrors on an instrumentation site.  percentile
    and snapshot answer from the (empty) shared state via the base."""

    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def histogram(self, *a, **k):
        pass

    def set_value(self, *a, **k):
        pass
