"""Stats client (reference stats/stats.go:31-161 StatsClient iface).

In-process counters/gauges/timings with tag support; snapshot() feeds both
the expvar-style /debug/vars JSON and the Prometheus text exposition at
/metrics (reference prometheus/prometheus.go)."""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class StatsClient:
    def __init__(self, tags: list[str] | None = None):
        self.tags = tags or []
        self._lock = threading.Lock()
        self._counts: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        # aggregated [count, sum] — NOT raw samples: always-on per-query
        # timings must stay O(1) memory over a server's lifetime
        self._timings: dict[str, list[float]] = defaultdict(
            lambda: [0, 0.0])

    def with_tags(self, *tags: str) -> "StatsClient":
        child = StatsClient(self.tags + list(tags))
        child._lock = self._lock  # shared metrics need the shared lock
        child._counts = self._counts
        child._gauges = self._gauges
        child._timings = self._timings
        return child

    def _key(self, name: str) -> str:
        if not self.tags:
            return name
        return name + "{" + ",".join(sorted(self.tags)) + "}"

    def count(self, name: str, value: float = 1, rate: float = 1.0):
        with self._lock:
            self._counts[self._key(name)] += value

    def gauge(self, name: str, value: float, rate: float = 1.0):
        with self._lock:
            self._gauges[self._key(name)] = value

    def timing(self, name: str, value_s: float, rate: float = 1.0):
        with self._lock:
            t = self._timings[self._key(name)]
            t[0] += 1
            t[1] += value_s

    def histogram(self, name: str, value: float, rate: float = 1.0):
        self.timing(name, value, rate)

    def set_value(self, name: str, value: str, rate: float = 1.0):
        with self._lock:
            self._gauges[self._key(name) + ":" + value] = 1

    class _Timer:
        def __init__(self, client, name):
            self.client, self.name = client, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.client.timing(self.name, time.perf_counter() - self.t0)

    def timer(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            timings = {
                k: {"count": v[0], "sum": v[1],
                    "mean": v[1] / v[0] if v[0] else 0}
                for k, v in self._timings.items()
            }
            return {"counts": dict(self._counts),
                    "gauges": dict(self._gauges),
                    "timings": timings}

    def prometheus_text(self) -> str:
        """Prometheus exposition format for /metrics
        (prometheus/prometheus.go:40)."""
        lines = []

        def fmt(name):
            base, _, tags = name.partition("{")
            base = "pilosa_tpu_" + base.replace(".", "_").replace("-", "_")
            return base + ("{" + tags if tags else "")

        snap = self.snapshot()
        for k, v in sorted(snap["counts"].items()):
            lines.append(f"# TYPE {fmt(k).split('{')[0]} counter")
            lines.append(f"{fmt(k)} {v}")
        for k, v in sorted(snap["gauges"].items()):
            lines.append(f"# TYPE {fmt(k).split('{')[0]} gauge")
            lines.append(f"{fmt(k)} {v}")
        for k, t in sorted(snap["timings"].items()):
            base = fmt(k).split("{")[0]
            lines.append(f"# TYPE {base}_seconds summary")
            lines.append(f"{base}_seconds_count {t['count']}")
            lines.append(f"{base}_seconds_sum {t['sum']}")
        return "\n".join(lines) + "\n"


class BucketHistogram:
    """Fixed-bucket counting histogram — bounded memory for always-on
    hot-path recording (the dispatch batcher's batch-size distribution).
    ``bounds`` are inclusive upper edges; values above the last bound land
    in the +Inf bucket."""

    def __init__(self, bounds):
        self.bounds = list(bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0

    def observe(self, v: float):
        with self._lock:
            self.count += 1
            self.total += v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        with self._lock:
            out = {f"le_{b}": c for b, c in zip(self.bounds, self._counts)}
            out["le_inf"] = self._counts[-1]
            out["count"] = self.count
            out["sum"] = self.total
            return out

    def prometheus_lines(self, name: str) -> list[str]:
        """Cumulative-bucket exposition (Prometheus histogram type)."""
        with self._lock:
            lines = [f"# TYPE {name} histogram"]
            cum = 0
            for b, c in zip(self.bounds, self._counts):
                cum += c
                lines.append(f'{name}_bucket{{le="{b}"}} {cum}')
            cum += self._counts[-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{name}_sum {self.total}")
            lines.append(f"{name}_count {self.count}")
            return lines


class ReservoirTimer:
    """Ring buffer of the last ``size`` duration samples; percentile()
    computes order statistics over a snapshot copy.  O(size) memory over
    a server's lifetime, like the aggregated timings above — but able to
    answer p50/p99 (the window-wait distribution the batch dispatcher
    publishes)."""

    def __init__(self, size: int = 512):
        self.size = size
        self._buf: list[float] = []
        self._pos = 0
        self._lock = threading.Lock()
        self.count = 0

    def observe(self, v: float):
        with self._lock:
            self.count += 1
            if len(self._buf) < self.size:
                self._buf.append(v)
            else:
                self._buf[self._pos] = v
                self._pos = (self._pos + 1) % self.size

    def percentile(self, q: float) -> float | None:
        with self._lock:
            buf = sorted(self._buf)
        if not buf:
            return None
        i = min(len(buf) - 1, int(q * (len(buf) - 1) + 0.5))
        return buf[i]

    def snapshot(self) -> dict:
        return {"count": self.count,
                "p50": self.percentile(0.5),
                "p99": self.percentile(0.99)}


class StatsdClient(StatsClient):
    """StatsClient that ALSO emits DataDog-flavored statsd UDP datagrams
    (reference statsd/statsd.go) while keeping the in-process snapshot so
    /debug/vars and /metrics stay live."""

    def __init__(self, host: str = "localhost", port: int = 8125,
                 tags: list[str] | None = None, sock=None):
        super().__init__(tags)
        import socket
        self._addr = (host, port)
        self._sock = sock if sock is not None else socket.socket(
            socket.AF_INET, socket.SOCK_DGRAM)

    def with_tags(self, *tags: str) -> "StatsdClient":
        child = StatsdClient(*self._addr, tags=self.tags + list(tags),
                             sock=self._sock)
        child._lock = self._lock
        child._counts = self._counts
        child._gauges = self._gauges
        child._timings = self._timings
        return child

    def _send(self, payload: str):
        if self.tags:
            payload += "|#" + ",".join(sorted(self.tags))
        try:
            self._sock.sendto(payload.encode(), self._addr)
        except OSError:
            pass  # stats must never take the server down (statsd.go:101)

    def count(self, name: str, value: float = 1, rate: float = 1.0):
        super().count(name, value, rate)
        self._send(f"{name}:{value}|c")

    def gauge(self, name: str, value: float, rate: float = 1.0):
        super().gauge(name, value, rate)
        self._send(f"{name}:{value}|g")

    def timing(self, name: str, value_s: float, rate: float = 1.0):
        super().timing(name, value_s, rate)
        self._send(f"{name}:{value_s * 1e3:.3f}|ms")

    def set_value(self, name: str, value: str, rate: float = 1.0):
        super().set_value(name, value, rate)
        self._send(f"{name}:{value}|s")


def make_stats_client(service: str = "expvar", host: str = "localhost:8125"
                      ) -> StatsClient:
    """Backend selection by config (server/server.go:268): "expvar" (also
    serves "prometheus" — both read the in-process snapshot), "statsd", or
    "none"/"nop"."""
    if service == "statsd":
        if ":" in host:
            h, _, p = host.rpartition(":")
            return StatsdClient(h or "localhost", int(p))
        return StatsdClient(host or "localhost", 8125)
    if service in ("none", "nop"):
        return NopStatsClient()
    return StatsClient()


class NopStatsClient(StatsClient):
    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass
