"""SLO engine: declarative objectives + burn-rate alerting over the
telemetry plane (docs/observability.md "SLOs & alerting").

The tree emits telemetry at three layers — per-query traces/profiles,
the device compile registry / launch ledger / time-series ring, and the
fleet rollup + event journal — but nothing *evaluates* any of it: an
operator learns about a violated latency objective by reading a
dashboard, after the bounded rings have rotated the evidence out.  This
module is the evaluation layer:

* **Declarative SLOs** — availability (non-5xx fraction of
  ``http.query``) and latency (fraction of queries under
  ``slo-latency-ms``) against an ``slo-target`` objective, judged with
  the classic multi-window burn-rate method (Google SRE workbook ch. 5):
  an alert fires only when BOTH a fast and a slow window burn error
  budget faster than ``BURN_THRESHOLD``x the sustainable rate — the fast
  window keeps resolution snappy after a heal, the slow window keeps a
  momentary blip from paging.  Windows are scaled to the existing
  ``timeseries-interval`` ring (no new sampling machinery): the counters
  ride ``Server.sample_timeseries`` as ``sloErrorsDelta`` /
  ``sloSlowQueriesDelta`` / ``httpQueriesDelta`` columns.
* **A pathology rules engine** — small predicates over the same
  time-series columns and stats counters for the known failure modes the
  event journal already names: retrace storm, hedge storm, eviction
  pressure, ingest backpressure, quarantine, breaker flapping.
* **Alert lifecycle** — ``alert.fire``/``alert.resolve`` events in the
  journal, ``alert.active`` / ``alerts.fired_total`` stats series,
  ``/debug/alerts``, an on-fire hook the flight recorder
  (utils/flightrec.py) hangs a rate-limited diagnostic capture on.

Evaluation runs on the Server's existing time-series monitor thread
(one pass per accepted sample) and must never block a query or a
scrape: each pass reads the ring snapshot and a handful of O(1) stats
counters, and the engine lock only guards its own alert table.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from . import events
from .locks import make_lock


def _wall_stamp() -> float: return time.time()  # display-only wall clock


# -- pathology thresholds (module-level so tests can tighten them) ----------
# retraces in the slow window: ONE retrace is already the PR-7-class red
# flag, but warmup replay legitimately re-traces a handful at startup
RETRACE_STORM = 3
# hedges per query over the slow window (plus an absolute floor so a
# single hedged query in an idle interval can't page)
HEDGE_STORM_FRAC = 0.5
HEDGE_STORM_MIN = 10
# device-budget evictions in the slow window: sustained churn, not the
# occasional eviction a working set near its budget produces
EVICTION_PRESSURE = 20
# ingest 503 rejections in the slow window: the committer's merge
# backlog latch is refusing acked writes
INGEST_BACKPRESSURE = 1
# breaker OPEN transitions in the slow window: >= 2 means a peer is
# cycling open -> half-open -> open (flapping), not just down once
BREAKER_FLAPS = 2


@dataclass
class AlertRule:
    """One declarative rule: ``check(ctx)`` returns a human-readable
    detail string while the condition holds, None when healthy.  The
    rule id is the operator contract — every id has a catalog row with
    a runbook line in docs/observability.md (the ``alert-names``
    two-way lint)."""
    id: str
    severity: str          # "page" | "ticket"
    summary: str
    check: Callable[["EvalContext"], Optional[str]]
    clear_after: int = 2   # consecutive healthy evaluations to resolve


RULES: dict[str, AlertRule] = {}


def alert_rule(rule_id: str, severity: str = "ticket", summary: str = "",
               clear_after: int = 2):
    """Register a rule under its literal id (the ``project_rule`` /
    failpoint-registry pattern — the analyzer's ``alert-names`` rule
    collects these literals for the docs catalog lint)."""
    def deco(fn):
        RULES[rule_id] = AlertRule(rule_id, severity, summary, fn,
                                   clear_after)
        return fn
    return deco


class EvalContext:
    """The read-only view one evaluation pass sees: the newest ring
    samples (delta + gauge columns, oldest first) plus the engine's
    objective knobs."""

    def __init__(self, samples: list[dict], engine: "SLOEngine"):
        self.samples = samples
        self.engine = engine

    def sum(self, col: str, n: int) -> float:
        """Sum of a delta column over the newest ``n`` samples."""
        return sum(s.get(col, 0.0) for s in self.samples[-n:])

    def last(self, col: str, default: float = 0.0) -> float:
        """Newest sample's value of a gauge column."""
        if not self.samples:
            return default
        return self.samples[-1].get(col, default)

    def burn(self, bad_col: str, total_col: str, n: int) -> float:
        """Burn rate over the newest ``n`` samples: the fraction of bad
        events divided by the error budget (1 - target).  1.0 means the
        budget is being spent exactly at the sustainable rate; an
        interval with no traffic burns nothing."""
        total = self.sum(total_col, n)
        if total <= 0:
            return 0.0
        bad = self.sum(bad_col, n)
        budget = max(1.0 - self.engine.target, 1e-9)
        return (bad / total) / budget


# -- burn-rate SLO rules ----------------------------------------------------


@alert_rule("slo-availability-burn", severity="page",
            summary="availability SLO error budget burning: 5xx "
                    "fraction of http.query over target in both windows")
def _availability_burn(ctx: EvalContext) -> Optional[str]:
    e = ctx.engine
    fast = ctx.burn("sloErrorsDelta", "httpQueriesDelta", e.fast_n)
    slow = ctx.burn("sloErrorsDelta", "httpQueriesDelta", e.slow_n)
    if fast > e.burn_threshold and slow > e.burn_threshold:
        return (f"5xx burn {fast:.1f}x fast / {slow:.1f}x slow "
                f"(target {e.target:g})")
    return None


@alert_rule("slo-latency-burn", severity="page",
            summary="latency SLO error budget burning: queries over "
                    "slo-latency-ms exceed target in both windows")
def _latency_burn(ctx: EvalContext) -> Optional[str]:
    e = ctx.engine
    fast = ctx.burn("sloSlowQueriesDelta", "httpQueriesDelta", e.fast_n)
    slow = ctx.burn("sloSlowQueriesDelta", "httpQueriesDelta", e.slow_n)
    if fast > e.burn_threshold and slow > e.burn_threshold:
        detail = (f"over-{e.latency_ms:g}ms burn {fast:.1f}x fast / "
                  f"{slow:.1f}x slow (target {e.target:g})")
        worst = e.worst_tenant()
        if worst is not None:
            detail += (f"; worst tenant {worst[0]} "
                       f"p99 {worst[1]:.0f}ms")
        return detail
    return None


# -- pathology rules (the failure modes the event journal names) ------------


@alert_rule("retrace-storm",
            summary="executables re-tracing in steady state (the "
                    "PR-7-class silent decode-bug red flag)")
def _retrace_storm(ctx: EvalContext) -> Optional[str]:
    n = ctx.sum("retracesDelta", ctx.engine.slow_n)
    if n >= RETRACE_STORM:
        return f"{n:g} retraces in the slow window"
    return None


@alert_rule("hedge-storm",
            summary="hedged reads on most queries: a replica is "
                    "persistently straggling")
def _hedge_storm(ctx: EvalContext) -> Optional[str]:
    hedges = ctx.sum("hedgesDelta", ctx.engine.slow_n)
    queries = ctx.sum("httpQueriesDelta", ctx.engine.slow_n)
    if hedges >= HEDGE_STORM_MIN \
            and hedges > HEDGE_STORM_FRAC * max(queries, 1.0):
        return f"{hedges:g} hedges over {queries:g} queries"
    return None


@alert_rule("eviction-pressure",
            summary="device budget thrashing: sustained eviction churn "
                    "instead of a resident working set")
def _eviction_pressure(ctx: EvalContext) -> Optional[str]:
    n = ctx.sum("evictionsDelta", ctx.engine.slow_n)
    if n >= EVICTION_PRESSURE:
        return f"{n:g} evictions in the slow window"
    return None


@alert_rule("ingest-backpressure",
            summary="streaming ingest refusing writes: the group "
                    "committer's merge backlog latched backpressure")
def _ingest_backpressure(ctx: EvalContext) -> Optional[str]:
    n = ctx.sum("ingestRejectedDelta", ctx.engine.slow_n)
    if n >= INGEST_BACKPRESSURE:
        return f"{n:g} ingest rejections in the slow window"
    return None


@alert_rule("quarantine",
            summary="fragments quarantined by corruption checks and "
                    "not yet repaired from replicas")
def _quarantine(ctx: EvalContext) -> Optional[str]:
    n = ctx.last("quarantinedFragments")
    if n > 0:
        return f"{n:g} fragment(s) quarantined"
    return None


@alert_rule("breaker-flapping",
            summary="a peer breaker cycling open/half-open/open "
                    "instead of staying up or staying down")
def _breaker_flapping(ctx: EvalContext) -> Optional[str]:
    n = ctx.sum("breakerOpensDelta", ctx.engine.slow_n)
    if n >= BREAKER_FLAPS:
        return f"{n:g} breaker opens in the slow window"
    return None


class SLOEngine:
    """Evaluates the registered rules against a TimeSeriesRing and keeps
    the active-alert table.  One instance per Server (it reads that
    server's ring); the rule REGISTRY is module-level and shared."""

    # burn-rate both windows must exceed before an SLO alert fires.
    # 10x means a 99.9% target's monthly budget would be gone in ~3
    # days — urgent, but tolerant of one bad scrape interval.
    BURN_THRESHOLD = 10.0
    # window pair scaled to the ring (classic 5m/1h compressed onto the
    # in-process window): fast = 5% of capacity, slow = 25%
    FAST_FRAC = 0.05
    SLOW_FRAC = 0.25
    HISTORY = 64  # fire/resolve transitions kept for /debug/alerts

    def __init__(self, ring, stats, *, latency_ms: float = 500.0,
                 target: float = 0.999, rules: str = "all",
                 logger=None, on_fire=None, tenant_registry=None):
        self.ring = ring
        self.stats = stats
        self.latency_ms = float(latency_ms)
        self.target = min(max(float(target), 0.0), 0.9999999)
        self.logger = logger
        self.on_fire = on_fire  # callable(alert_dict) on fire transition
        self.tenant_registry = tenant_registry
        self.burn_threshold = self.BURN_THRESHOLD
        cap = max(getattr(ring, "capacity", 1), 1)
        self.fast_n = max(2, int(cap * self.FAST_FRAC))
        self.slow_n = max(self.fast_n * 3, int(cap * self.SLOW_FRAC))
        self.rules = self._select(rules)
        self.enabled = bool(self.rules)
        self._lock = make_lock("slo")
        self.active: dict[str, dict] = {}
        self.fired_total = 0
        self.resolved_total = 0
        self.evaluations = 0
        self._quiet: dict[str, int] = {}  # consecutive healthy evals
        self._history: deque = deque(maxlen=self.HISTORY)

    def _select(self, spec: str) -> dict[str, AlertRule]:
        spec = (spec or "all").strip()
        if spec in ("off", "none", ""):
            return {}
        if spec == "all":
            return dict(RULES)
        chosen = {}
        for rid in (s.strip() for s in spec.split(",")):
            if not rid:
                continue
            if rid in RULES:
                chosen[rid] = RULES[rid]
            elif self.logger is not None:
                self.logger.error(
                    f"alert-rules names unknown rule '{rid}' "
                    f"(known: {', '.join(sorted(RULES))})")
        return chosen

    def worst_tenant(self) -> tuple[str, float] | None:
        """Optional per-tenant scoping (the PR 17 registry): the tenant
        with the highest p99 over the objective, for the latency
        alert's detail line.  None when no tenant is over or the
        registry is absent/empty."""
        reg = self.tenant_registry
        if reg is None:
            return None
        worst = None
        for tenant, cols in reg.snapshot().items():
            p99 = cols.get("p99Ms") or 0.0
            if p99 > self.latency_ms and \
                    (worst is None or p99 > worst[1]):
                worst = (tenant, p99)
        return worst

    def evaluate(self) -> None:
        """One evaluation pass over the newest slow-window samples.
        Runs on the Server's time-series monitor thread right after an
        accepted sample; never raises (a dead evaluator is a muted
        pager — the PR 6 swallow class is logged per rule instead)."""
        if not self.enabled:
            return
        samples = self.ring.last(self.slow_n)
        ctx = EvalContext(samples, self)
        firing: dict[str, str] = {}
        for rid, rule in self.rules.items():
            try:
                detail = rule.check(ctx)
            except Exception as e:
                if self.logger is not None:
                    self.logger.error(f"alert rule {rid} failed: {e}")
                continue
            if detail is not None:
                firing[rid] = detail
        fired, resolved = [], []
        with self._lock:
            self.evaluations += 1
            for rid, detail in firing.items():
                self._quiet[rid] = 0
                cur = self.active.get(rid)
                if cur is not None:
                    cur["detail"] = detail  # keep the newest evidence
                    continue
                rule = self.rules[rid]
                alert = {"id": rid, "severity": rule.severity,
                         "summary": rule.summary, "detail": detail,
                         "sinceWall": _wall_stamp(),
                         "sinceMono": time.monotonic(),
                         "firedAtEvaluation": self.evaluations}
                self.active[rid] = alert
                self.fired_total += 1
                fired.append(dict(alert))
            for rid in list(self.active):
                if rid in firing:
                    continue
                quiet = self._quiet.get(rid, 0) + 1
                self._quiet[rid] = quiet
                if quiet >= self.rules[rid].clear_after:
                    alert = self.active.pop(rid)
                    self.resolved_total += 1
                    resolved.append(alert)
            n_active = len(self.active)
            for a in fired:
                self._history.append(
                    {"action": "fire", "id": a["id"], "wall": a["sinceWall"],
                     "severity": a["severity"], "detail": a["detail"]})
            for a in resolved:
                self._history.append(
                    {"action": "resolve", "id": a["id"],
                     "wall": _wall_stamp(), "severity": a["severity"],
                     "detail": a["detail"]})
        # emissions OUTSIDE the lock: the journal, stats, logger, and
        # the flight-recorder hook acquire their own leaf locks
        for a in fired:
            events.emit("alert.fire", alert=a["id"],
                        severity=a["severity"], detail=a["detail"])
            if self.stats is not None:
                self.stats.count("alerts.fired_total")
            if self.logger is not None:
                self.logger.error(
                    f"ALERT fire [{a['severity']}] {a['id']}: "
                    f"{a['detail']}")
            if self.on_fire is not None:
                try:
                    self.on_fire(a)
                except Exception as e:
                    if self.logger is not None:
                        self.logger.error(
                            f"alert on-fire hook failed: {e}")
        for a in resolved:
            events.emit("alert.resolve", alert=a["id"],
                        severity=a["severity"])
            if self.logger is not None:
                self.logger.info(f"ALERT resolve {a['id']}")
        if self.stats is not None:
            self.stats.gauge("alert.active", n_active)

    def vars_summary(self) -> dict:
        """The compact form embedded in /debug/vars (and shipped per
        node by the fleet rollup — keep it small on the wire)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "firedTotal": self.fired_total,
                "resolvedTotal": self.resolved_total,
                "evaluations": self.evaluations,
                "active": {rid: {"severity": a["severity"],
                                 "detail": a["detail"],
                                 "sinceWall": a["sinceWall"]}
                           for rid, a in self.active.items()},
            }

    def snapshot(self) -> dict:
        """The full /debug/alerts body: objectives, windows, the active
        table with durations, recent transitions, and the rule list."""
        now = time.monotonic()
        interval = getattr(self.ring, "interval_s", 0.0)
        with self._lock:
            active = {}
            for rid, a in self.active.items():
                row = {k: v for k, v in a.items() if k != "sinceMono"}
                row["durationS"] = round(now - a["sinceMono"], 3)
                active[rid] = row
            return {
                "enabled": self.enabled,
                "target": self.target,
                "latencyMs": self.latency_ms,
                "burnThreshold": self.burn_threshold,
                "windows": {"fastN": self.fast_n, "slowN": self.slow_n,
                            "fastS": round(self.fast_n * interval, 3),
                            "slowS": round(self.slow_n * interval, 3)},
                "evaluations": self.evaluations,
                "firedTotal": self.fired_total,
                "resolvedTotal": self.resolved_total,
                "active": active,
                "history": list(self._history),
                "rules": [{"id": r.id, "severity": r.severity,
                           "summary": r.summary,
                           "clearAfter": r.clear_after}
                          for r in self.rules.values()],
            }
