"""Crash-durable file replacement.

``buffering=0`` / plain writes land in the page cache; ``os.replace``
orders the rename but not the data, so a crash shortly after an
acknowledged snapshot could surface an empty or stale file.  The durable
sequence is: flush+fsync the temp file, rename, then fsync the DIRECTORY
so the rename itself is on stable storage (the same discipline the
reference gets from bolt/roaring file syncs)."""

from __future__ import annotations

import os
import zlib


def checksum(data, crc: int = 0) -> int:
    """File-format checksum for snapshots and WAL frames
    (docs/robustness.md "Durability & recovery").

    zlib's CRC-32 (IEEE polynomial): the only C-speed CRC in the
    stdlib — a pure-Python CRC32C (Castagnoli) table loop would cap
    snapshot verification at a few MB/s, and the container bakes in no
    crc32c package.  Detection power is equivalent for the corruptions
    this layer guards against (torn writes, bit rot, truncation).
    Chainable: ``checksum(b, checksum(a))`` == ``checksum(a + b)``.
    Accepts any buffer (bytes, memoryview, numpy array data)."""
    return zlib.crc32(data, crc) & 0xFFFFFFFF


def fsync_file(f):
    """Flush a writable file object's data to stable storage."""
    f.flush()
    os.fsync(f.fileno())


def fsync_dir(path: str):
    """fsync a directory so a completed rename within it is durable.
    Best-effort: platforms/filesystems that refuse O_RDONLY-dir fsync
    (some network mounts) degrade to the pre-fsync behavior."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_replace(tmp: str, path: str):
    """``os.replace(tmp, path)`` + directory fsync (the temp file must
    already be fsynced by the writer — see fsync_file)."""
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")
