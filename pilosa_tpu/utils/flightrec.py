"""Flight recorder: on-trigger diagnostic bundles (docs/observability.md
"SLOs & alerting").

Every debug surface in the tree is a bounded ring: the time-series
window, the event journal, the slow-query log, the span buffer, the
launch ledger.  That boundedness is what makes them safe to run
always-on — and what makes a 3am p99 spike unforensicable by 9am, after
the rings have rotated.  The flight recorder closes that gap: when the
SLO engine (utils/slo.py) fires an alert — or an operator asks via
``POST /debug/bundle`` / ``pilosa-tpu bundle`` — it snapshots the whole
debug plane into one JSON bundle on disk:

* ``/debug/vars`` (the full expvar body, alerts included)
* the full time-series window
* the event-journal tail
* the slow-query log with per-entry profile trees
* the compile registry and launch ledger
* the active alert table

Bundles live under ``<data-dir>/flightrec/`` inside a
``flight-recorder-mb`` disk budget, LRU-pruned by file mtime (the
compile-cache prune discipline) — oldest bundles go first, the bundle
just written is never pruned.  On-fire captures are rate-limited
(``MIN_INTERVAL_S``) so a flapping alert cannot fill the budget with
near-identical bundles; on-demand captures bypass the limit.

Capture runs on the Server's monitor thread (or a handler thread for
on-demand requests) and must never fail the caller: collection and
write errors are logged and counted, never raised.
"""

from __future__ import annotations

import json
import os
import re
import time

from .locks import make_lock


def _wall_stamp() -> float: return time.time()  # display-only wall clock


_REASON_SAFE = re.compile(r"[^a-zA-Z0-9._-]+")


class FlightRecorder:
    # seconds between automatic (on-fire) captures; on-demand captures
    # pass force=True and skip the limiter
    MIN_INTERVAL_S = 60.0

    def __init__(self, directory: str, budget_mb: int = 64,
                 min_interval_s: float | None = None,
                 logger=None, stats=None):
        self.dir = directory
        self.budget_mb = max(int(budget_mb), 1)
        self.min_interval_s = self.MIN_INTERVAL_S \
            if min_interval_s is None else float(min_interval_s)
        self.logger = logger
        self.stats = stats
        self._lock = make_lock("flightrec")
        self._seq = 0
        self._last_mono: float | None = None
        self.captures = 0
        self.rate_limited = 0
        self.errors = 0
        self.pruned = 0
        # {"path","reason","wall","bytes"} of the newest bundle — the
        # stamp /debug/vars and the diagnostics payload surface
        self.last: dict | None = None

    def capture(self, reason: str, collect, force: bool = False
                ) -> str | None:
        """Write one bundle; returns its path, or None when rate-limited
        or failed.  ``collect`` is a zero-arg callable building the
        payload dict — called OUTSIDE the lock (it walks the debug
        surfaces, which take their own leaf locks)."""
        reason = _REASON_SAFE.sub("-", reason or "manual")[:64] or "manual"
        now = time.monotonic()
        with self._lock:
            if not force and self._last_mono is not None \
                    and now - self._last_mono < self.min_interval_s:
                self.rate_limited += 1
                return None
            # reserve the slot before the (slow) collect so a burst of
            # fire transitions can't all pass the limiter together
            self._last_mono = now
            self._seq += 1
            seq = self._seq
        try:
            payload = collect()
            payload = dict(payload)
            payload.setdefault("reason", reason)
            payload["wall"] = _wall_stamp()
            os.makedirs(self.dir, exist_ok=True)
            name = f"bundle-{int(payload['wall'])}-{seq:04d}-{reason}.json"
            path = os.path.join(self.dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            size = os.path.getsize(path)
        except Exception as e:
            self.errors += 1
            if self.logger is not None:
                self.logger.error(f"flight-recorder capture failed: {e}")
            return None
        with self._lock:
            self.captures += 1
            self.last = {"path": path, "reason": reason,
                         "wall": payload["wall"], "bytes": size}
        if self.stats is not None:
            self.stats.count("flightrec.captures")
        self.prune(keep=path)
        if self.logger is not None:
            self.logger.info(
                f"flight-recorder bundle {name} ({size >> 10} KiB)")
        return path

    def prune(self, keep: str | None = None) -> int:
        """LRU-prune the bundle directory to the MB budget by file
        mtime (the warmup compile-cache discipline); ``keep`` is never
        deleted even when a single bundle exceeds the budget."""
        try:
            entries = []
            for name in os.listdir(self.dir):
                if not (name.startswith("bundle-")
                        and name.endswith(".json")):
                    continue
                path = os.path.join(self.dir, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue  # raced a concurrent prune
                entries.append((st.st_mtime, st.st_size, path))
        except OSError:
            return 0  # directory absent: nothing captured yet
        budget = self.budget_mb << 20
        total = sum(size for _, size, _ in entries)
        deleted = 0
        for _, size, path in sorted(entries):
            if total <= budget:
                break
            if keep is not None and os.path.abspath(path) \
                    == os.path.abspath(keep):
                continue
            try:
                os.remove(path)
                total -= size
                deleted += 1
            except OSError as e:
                if self.logger is not None:
                    self.logger.error(
                        f"flight-recorder prune failed for {path}: {e}")
        if deleted:
            with self._lock:
                self.pruned += deleted
        return deleted

    def disk_bytes(self) -> int:
        try:
            return sum(
                os.path.getsize(os.path.join(self.dir, n))
                for n in os.listdir(self.dir)
                if n.startswith("bundle-") and n.endswith(".json"))
        except OSError:
            return 0

    def snapshot(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "budgetMb": self.budget_mb,
                    "minIntervalS": self.min_interval_s,
                    "captures": self.captures,
                    "rateLimited": self.rate_limited,
                    "errors": self.errors, "pruned": self.pruned,
                    "diskBytes": self.disk_bytes(),
                    "last": dict(self.last) if self.last else None}
