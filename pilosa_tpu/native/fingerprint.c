/* Query fingerprint scanner: the C hot path behind
 * pilosa_tpu/executor/prepared.py's fingerprint().
 *
 * Replaces every bare integer literal in a PQL text with '?' and collects
 * the literal values, exactly like the _FP regex (prepared.py): a literal
 * is an optional '-' followed by digits, where the characters on both
 * sides are outside [A-Za-z0-9_.:-] (so digits inside identifiers,
 * floats, timestamps like 2017-01-01T00:00, and key:ranges never match),
 * and single-/double-quoted strings (with backslash escapes) are opaque.
 *
 * The reference parses every query from scratch per request (pql/pql.peg
 * generated machine); at Go speeds that is fine, but here the fingerprint
 * gate runs in front of the prepared-statement cache on every request and
 * a Python regex pass costs ~25 ms per 1024-call batch (~24 us/query of
 * GIL time) — more than the entire per-query budget at the 10x-CPU
 * target.  This scanner runs the same pass at memory speed.
 *
 * Returns the number of literals found (>= 0), writing the template text
 * to *tmpl (always <= n bytes) and the values to vals.  Returns -1 when a
 * literal cannot be represented (digit run longer than 18 chars could
 * overflow int64); the caller falls back to the Python path, which has
 * arbitrary-precision ints.
 */

#include <stdint.h>

/* [A-Za-z0-9_.:-] — the regex's \w plus .:- */
static inline int boundary_class(unsigned char c) {
    return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') ||
           (c >= '0' && c <= '9') || c == '_' || c == '.' || c == ':' ||
           c == '-';
}

static inline int is_digit(unsigned char c) { return c >= '0' && c <= '9'; }

long fingerprint_scan(const unsigned char *src, long n, unsigned char *tmpl,
                      long *tmpl_len, int64_t *vals, long max_vals) {
    long i = 0, o = 0, nv = 0;
    /* prev: the byte before the current scan position ('\0' at start —
     * not in the class, matching the regex's lookbehind at offset 0). */
    unsigned char prev = 0;
    while (i < n) {
        unsigned char c = src[i];
        if (c == '\'' || c == '"') {
            /* try to consume a quoted string; on no closing quote the
             * quote is an ordinary character (the regex alternation would
             * fail the same way and move on one char) */
            long j = i + 1;
            while (j < n && src[j] != c) {
                if (src[j] == '\\' && j + 1 < n)
                    j++; /* escaped char */
                j++;
            }
            if (j < n) { /* closed: copy verbatim, contents are opaque */
                for (long k = i; k <= j; k++)
                    tmpl[o++] = src[k];
                prev = c;
                i = j + 1;
                continue;
            }
            tmpl[o++] = c;
            prev = c;
            i++;
            continue;
        }
        if ((is_digit(c) || (c == '-' && i + 1 < n && is_digit(src[i + 1])))
            && !boundary_class(prev)) {
            long j = i, start;
            int neg = 0;
            if (src[j] == '-') {
                neg = 1;
                j++;
            }
            start = j;
            while (j < n && is_digit(src[j]))
                j++;
            if (j < n && boundary_class(src[j])) {
                /* trailing boundary fails (identifier/float/timestamp):
                 * the whole run is ordinary text */
                for (long k = i; k < j; k++)
                    tmpl[o++] = src[k];
                prev = src[j - 1];
                i = j;
                continue;
            }
            if (j - start > 18)
                return -1; /* may overflow int64: Python path */
            {
                int64_t v = 0;
                for (long k = start; k < j; k++)
                    v = v * 10 + (src[k] - '0');
                if (nv >= max_vals)
                    return -1;
                vals[nv++] = neg ? -v : v;
            }
            tmpl[o++] = '?';
            prev = src[j - 1];
            i = j;
            continue;
        }
        tmpl[o++] = c;
        prev = c;
        i++;
    }
    *tmpl_len = o;
    return nv;
}
