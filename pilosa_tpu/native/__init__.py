"""Native (C) runtime helpers.

The compute path is JAX/XLA; these are host-side runtime hot spots where
Python-level cost caps serving throughput (the reference spends the same
cycles in compiled Go).  Each helper is optional: the .so is built from
the checked-in C source with the system compiler on first import and every
caller keeps a pure-Python fallback, so a missing toolchain degrades to
the slow path rather than failing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))


def _build_and_load(name: str):
    """Compile native/<name>.c to _<name>.so (if stale) and dlopen it.
    Returns None on any failure — callers must treat the native path as
    an optimization, never a requirement."""
    src = os.path.join(_DIR, f"{name}.c")
    so = os.path.join(_DIR, f"_{name}.so")
    if os.path.exists(so) and \
            os.path.getmtime(so) >= os.path.getmtime(src):
        try:
            return ctypes.CDLL(so)
        except OSError:
            pass  # corrupt / wrong-arch artifact: rebuild below
    try:
        # build to a temp file + atomic rename: concurrent importers
        # (test workers, multi-server benches) must not dlopen a
        # half-written .so
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        subprocess.run(
            ["cc", "-O3", "-shared", "-fPIC", "-o", tmp, src],
            check=True, capture_output=True, timeout=60)
        os.replace(tmp, so)
        return ctypes.CDLL(so)
    # lint: allow(swallowed-exception) — the native extension is an
    # optional accelerator: no cc / no toolchain falls back to the pure-
    # python path, and callers treat None as exactly that
    except Exception:
        return None


_fp_lib = _build_and_load("fingerprint")
if _fp_lib is not None:
    _fp_lib.fingerprint_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_long,
    ]
    _fp_lib.fingerprint_scan.restype = ctypes.c_long


def fingerprint_native(query: str):
    """(template, values int64 ndarray) via the C scanner, or None when
    the native library is unavailable or the query needs the Python path
    (int64 overflow)."""
    if _fp_lib is None:
        return None
    if not query.isascii():
        # the regex's \w matches Unicode word chars in lookarounds; the C
        # scanner is byte-wise ASCII — non-ASCII queries (keys are quoted,
        # but be exact) take the Python path
        return None
    b = query.encode("utf-8")
    n = len(b)
    tmpl = ctypes.create_string_buffer(n + 1)
    vals = np.empty(n // 2 + 1, dtype=np.int64)
    out_len = ctypes.c_long()
    nv = _fp_lib.fingerprint_scan(
        b, n, tmpl, ctypes.byref(out_len),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), vals.size)
    if nv < 0:
        return None
    return tmpl.raw[:out_len.value].decode("utf-8"), vals[:nv]
