"""Persistent XLA compile cache wiring (docs/warmup.md "Compile
cache").

jax ships an on-disk compilation cache (keyed by a hash of the lowered
HLO + compile options + backend version); pointing it under data-dir
means a restarted process REUSES yesterday's executables instead of
re-lowering and re-compiling them.  The warmup replayer
(warmup/replayer.py) drives the top-N corpus queries through the real
compile paths at startup, so every hit lands here at disk speed instead
of XLA-compile speed — that's the whole warm-start story: the corpus
remembers WHAT to compile, this cache remembers the COMPILED BYTES.

This module is deliberately thin glue:

* ``configure(dir)`` flips the three jax config knobs (cache dir, and
  both min-compile-time/min-entry-size floors to zero — the defaults
  skip sub-second compiles, which on CPU smoke runs is everything).
  Gated in try/except: an older jax without the knobs, or no jax at
  all, degrades to no persistent cache, never a failed boot.
* ``prune(dir, max_mb)`` LRU-prunes the cache directory to the
  ``compile-cache-mb`` bound by file mtime (jax touches entries on
  read), oldest first.  Runs at startup (before the cache is hot) and
  on clean shutdown.

The cache directory defaults to ``<data-dir>/.compile-cache`` (knob
``compile-cache-dir``); ``off`` disables the whole subsystem.
"""

from __future__ import annotations

import os

# Hidden: the holder scans data-dir subdirectories as indexes and
# skips dot-dirs, so the cache must not look like an index.
DEFAULT_SUBDIR = ".compile-cache"


def resolve_dir(cache_dir: str, data_dir: str | None) -> str | None:
    """The effective cache directory for the config knobs: explicit
    path wins, "" means <data-dir>/.compile-cache, "off" (or "" with no
    data dir) disables."""
    if cache_dir == "off":
        return None
    if cache_dir:
        return cache_dir
    if data_dir:
        return os.path.join(data_dir, DEFAULT_SUBDIR)
    return None


def configure(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``;
    returns False (disabled) when jax is missing or too old — a warm
    start is an optimization, never a boot requirement."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default floors skip fast/small compiles; the corpus replays
        # exactly the programs we want cached, so cache everything
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    # lint: allow(swallowed-exception) — no jax / old jax / unwritable
    # dir all mean "no persistent cache", a pure perf downgrade the
    # warmup status surface reports as cacheEnabled=false
    except Exception:
        return False


def cache_stats(cache_dir: str) -> dict:
    """{files, bytes} for the status surfaces; never raises."""
    files = total = 0
    try:
        for name in os.listdir(cache_dir):
            p = os.path.join(cache_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            if os.path.isfile(p):
                files += 1
                total += st.st_size
    except OSError:
        pass
    return {"files": files, "bytes": total}


def prune(cache_dir: str, max_mb: int) -> dict:
    """Delete oldest-by-mtime cache files until the directory fits
    ``max_mb`` (0 = unbounded).  Returns {files, bytes, removed,
    removedBytes}; never raises — a prune failure costs disk, not
    availability."""
    entries = []
    total = 0
    try:
        for name in os.listdir(cache_dir):
            p = os.path.join(cache_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            if os.path.isfile(p):
                entries.append((st.st_mtime, st.st_size, p))
                total += st.st_size
    except OSError:
        return {"files": 0, "bytes": 0, "removed": 0, "removedBytes": 0}
    removed = removed_bytes = 0
    if max_mb and max_mb > 0:
        limit = max_mb * 1024 * 1024
        entries.sort()  # oldest mtime first — LRU victims
        i = 0
        while total > limit and i < len(entries):
            _, size, p = entries[i]
            i += 1
            try:
                os.unlink(p)
            except OSError:
                continue
            total -= size
            removed += 1
            removed_bytes += size
    return {"files": len(entries) - removed, "bytes": total,
            "removed": removed, "removedBytes": removed_bytes}
