"""AOT warmup replayer + warm-start coordinator (docs/warmup.md
"Warmup lifecycle").

The coordinator owns the whole warm-start surface for one Server:

* at boot (after local WAL replay has made the holder queryable, and
  concurrent with the rest of startup — cluster join, serve loop) it
  loads the signature corpus, seeds the traffic recorder with it, and
  — when there is anything worth warming — replays the top-N corpus
  queries through the REAL executor paths before the node reports
  READY.  Replay through ``Executor.execute`` is deliberate: it drives
  the same WholeQueryRunner/MeshExecutor compile paths production
  traffic does (hitting the persistent compile cache at disk speed),
  and rebuilds the prepared-statement cache entries as a side effect,
  so a prepared hit survives a deploy.
* while serving it flushes the recorder to the corpus on a fixed
  cadence (its own monitor thread), so a kill -9 loses at most a few
  seconds of hit-count drift.
* every failure degrades: a corrupt/empty/stale corpus means fewer (or
  zero) replays, a replay error (index dropped since the corpus was
  written) is counted and skipped, the budget expiring abandons the
  remaining entries — warmup can make READY *later*, never *absent*.

Status (phase, progress, compile-seconds-saved) feeds /status,
/debug/vars, the event journal (``warmup.start``/``warmup.done``) and
the ``warmup.*`` gauges.
"""

from __future__ import annotations

import threading
import time
from time import perf_counter

from ..utils import events
from ..utils.devobs import COMPILES
from ..utils.locks import make_lock
from .corpus import CorpusRecorder, SignatureCorpus, top_n

PHASE_COLD = "cold"        # no corpus / warmup disabled: straight to READY
PHASE_WARMING = "warming"  # replaying — /status not READY yet
PHASE_READY = "ready"


def _wall_stamp() -> float: return time.time()  # display-only wall clock


class WarmupCoordinator:
    """One per Server: corpus + recorder + the warmup/flush thread."""

    FLUSH_INTERVAL_S = 5.0

    def __init__(self, executor, path: str, top_n: int = 32,
                 budget_s: float = 30.0, logger=None, stats=None):
        self.executor = executor
        self.path = path
        self.top_n = max(int(top_n), 0)
        self.budget_s = float(budget_s)
        self.logger = logger
        self.stats = stats
        self.corpus = SignatureCorpus(path)
        # the compaction survivor set keeps a margin beyond the replay
        # set so ranking churn near the cut line doesn't lose history
        self.recorder = CorpusRecorder(keep_n=max(self.top_n, 16) * 4)
        self._lock = make_lock("warmup")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.on_ready = None  # Server hook: flip node state to READY
        # status surface (all read under _lock via status())
        self.phase = PHASE_COLD
        self.corpus_entries = 0
        self.planned = 0
        self.replayed = 0
        self.errors = 0
        self.skipped = 0
        self.saved_compile_s = 0.0
        self.warm_compile_s = 0.0
        self.retraces_during_warm = 0
        self.elapsed_s = 0.0
        self.cache_enabled = False
        self._pending: list[dict] = []

    # -- boot --------------------------------------------------------------

    def open(self) -> bool:
        """Load the corpus (torn tail truncated, bad records dropped),
        seed the recorder, pick the replay set.  Returns True when the
        node should enter the warming phase.  Never raises."""
        self.corpus.open()
        folded = SignatureCorpus.load(self.path)
        self.recorder.seed(folded)
        pending = top_n(list(folded.values()),
                        self.top_n) if self.top_n > 0 else []
        with self._lock:
            self.corpus_entries = len(folded)
            self._pending = pending
            self.planned = len(pending)
            self.phase = PHASE_WARMING if pending else PHASE_READY
            return self.phase == PHASE_WARMING

    def start(self):
        """Spawn the warmup+flush thread (daemon: telemetry-grade)."""
        self._thread = threading.Thread(target=self._run, name="warmup",
                                        daemon=True)
        self._thread.start()

    def _run(self):
        try:
            warming = False
            with self._lock:
                warming = self.phase == PHASE_WARMING
            if warming:
                self._warm()
        finally:
            with self._lock:
                self.phase = PHASE_READY
            cb = self.on_ready
            if cb is not None:
                try:
                    cb()
                # lint: allow(swallowed-exception) — the READY callback
                # flips cluster state; a failure there leaves the node
                # warming-visible but the flush loop (and serving) alive
                except Exception:
                    pass
        while not self._stop.wait(self.FLUSH_INTERVAL_S):
            self.recorder.flush(self.corpus)

    # -- the replay itself -------------------------------------------------

    def _warm(self):
        with self._lock:
            pending = list(self._pending)
        t0 = perf_counter()
        c0 = COMPILES.totals()
        events.emit("warmup.start", entries=self.corpus_entries,
                    topN=len(pending), budgetS=round(self.budget_s, 1))
        expected_s = 0.0
        for rec in pending:
            if self._stop.is_set() or \
                    perf_counter() - t0 >= self.budget_s:
                with self._lock:
                    self.skipped = len(pending) - self.replayed \
                        - self.errors
                break
            try:
                self.executor.execute(rec["index"], rec["query"])
                expected_s += float(rec.get("compileS", 0.0))
                with self._lock:
                    self.replayed += 1
            except Exception as e:
                # a stale corpus entry (index dropped, field renamed)
                # must not fail READY: count it, tell the log, move on
                with self._lock:
                    self.errors += 1
                log = self.logger
                if log is not None:
                    try:
                        log.event("warmup.replay_error",
                                  index=rec.get("index", ""),
                                  template=rec.get("template", ""),
                                  error=str(e))
                    # lint: allow(swallowed-exception) — a closed log
                    # stream costs a line; the error is already counted
                    except Exception:
                        pass
        c1 = COMPILES.totals()
        warm_s = max(c1["compileSecondsTotal"]
                     - c0["compileSecondsTotal"], 0.0)
        with self._lock:
            self.elapsed_s = round(perf_counter() - t0, 3)
            self.warm_compile_s = round(warm_s, 4)
            # what the corpus said these programs cost to compile cold,
            # minus what the warm replay actually paid (persistent-cache
            # hits compile at disk speed) — the headline number
            self.saved_compile_s = round(max(expected_s - warm_s, 0.0), 4)
            self.retraces_during_warm = c1["retraces"] - c0["retraces"]
            replayed, errors, skipped = (self.replayed, self.errors,
                                         self.skipped)
            elapsed, saved = self.elapsed_s, self.saved_compile_s
        stats = self.stats
        if stats is not None:
            stats.gauge("warmup.replayed", replayed)
            stats.gauge("warmup.errors", errors)
            stats.gauge("warmup.saved_seconds", saved)
        events.emit("warmup.done", replayed=replayed, errors=errors,
                    skipped=skipped, elapsedS=elapsed, savedS=saved,
                    compileS=round(warm_s, 4),
                    retraces=self.retraces_during_warm)

    # -- serving-time surfaces ---------------------------------------------

    def note_query(self, index: str, qtext: str):
        self.recorder.note(index, qtext)

    def warming(self) -> bool:
        with self._lock:
            return self.phase == PHASE_WARMING

    def status(self) -> dict:
        with self._lock:
            return {"phase": self.phase,
                    "corpusEntries": self.corpus_entries,
                    "topN": self.top_n,
                    "budgetS": self.budget_s,
                    "planned": self.planned,
                    "replayed": self.replayed,
                    "errors": self.errors,
                    "skipped": self.skipped,
                    "elapsedS": self.elapsed_s,
                    "compileS": self.warm_compile_s,
                    "savedCompileS": self.saved_compile_s,
                    "retracesDuringWarm": self.retraces_during_warm,
                    "cacheEnabled": self.cache_enabled,
                    "recorder": self.recorder.snapshot(),
                    "corpusWriteErrors": self.corpus.write_errors}

    # -- shutdown ----------------------------------------------------------

    def close(self):
        """Stop the thread, take a final flush so the corpus reflects
        the full run (clean shutdowns lose nothing; kill -9 loses at
        most FLUSH_INTERVAL_S of drift)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self.recorder.flush(self.corpus)
        self.corpus.close()
