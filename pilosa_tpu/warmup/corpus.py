"""Durable signature corpus: what this process compiles, persisted
(docs/warmup.md "Corpus format").

A restarted process can only warm what it remembers.  The compile
registry (utils/devobs.py) knows every program signature this process
traced — but its signatures are digests of process-local cache keys
(plan reprs, exec sequence numbers) and cannot be replayed after a
restart.  What CAN be replayed is the query text that produced them:
feeding the text back through the real executor rebuilds the same plans,
compiles the same programs (now against the persistent compile cache —
warmup/compile_cache.py), and repopulates the prepared-statement cache
as a side effect.

So the corpus records, per (index, template) — the template is the
prepared-cache fingerprint with literals slotted out, i.e. the params
schema: a sample query text, the last whole-query program signature it
launched, the registry's shape fingerprint + compile seconds for that
signature, a hit count, and a last-used wall stamp.  Storage is the
PR 6/9/15 frame discipline: a ``PTPUSIG1`` magic prefix then
length+CRC framed JSON records, one record per frame, torn tail
truncated at the last valid frame boundary on open.  Corruption beyond
the frame scan (bad JSON, wrong schema version, missing keys) drops the
RECORD, never the process: a warm start is an optimization, so every
read path here degrades to "fewer records" and ultimately to a cold
start — ``load`` never raises.

Compaction rewrites the log to the top-N records by traffic via the
atomic tmp+fsync+rename pattern (storage WAL checkpoint discipline), so
the log stays bounded no matter how long the process serves.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time

from ..utils.durable import checksum
from ..utils.locks import make_lock

CORPUS_MAGIC = b"PTPUSIG1"
_FRAME_HDR = struct.Struct("<II")  # payload length, crc32(payload)

# Bump when the record shape changes incompatibly; loaders drop records
# whose "v" doesn't match (stale-schema corpus degrades to cold start).
SCHEMA_VERSION = 1


def _wall_stamp() -> float: return time.time()  # display-only wall clock


def _frame(payload: bytes) -> bytes:
    # header + payload in ONE write (the WAL frame discipline): a torn
    # write truncates at a frame boundary, never interleaves
    return _FRAME_HDR.pack(len(payload), checksum(payload)) + payload


def _scan_valid(data: bytes) -> int:
    """Byte offset of the end of the valid frame prefix (magic
    included); len(magic) when the magic itself is wrong."""
    if not data.startswith(CORPUS_MAGIC):
        return len(CORPUS_MAGIC)
    pos = len(CORPUS_MAGIC)
    while pos + _FRAME_HDR.size <= len(data):
        ln, crc = _FRAME_HDR.unpack_from(data, pos)
        end = pos + _FRAME_HDR.size + ln
        if end > len(data) or checksum(data[pos + _FRAME_HDR.size:
                                            end]) != crc:
            break
        pos = end
    return pos


class SignatureCorpus:
    """Framed on-disk signature log, append + atomic compaction.

    Mirrors EventJournal's log handling (utils/events.py): open
    truncates the torn tail, appends are flushed per batch but not
    fsynced (the corpus is telemetry-grade — losing the last few
    seconds of hit counts costs nothing), compaction IS fsynced because
    it replaces the whole file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = make_lock("warmup-corpus")
        self._fh = None
        self.frames_appended = 0
        self.write_errors = 0

    # -- lifecycle ---------------------------------------------------------

    def open(self):
        """Open (or create) the log, truncating any torn tail.  A
        garbage prefix (wrong magic) rewrites the file empty — better an
        empty corpus than a refused warm start.  Never raises."""
        try:
            if os.path.exists(self.path):
                with open(self.path, "rb") as f:
                    data = f.read()
                valid_end = _scan_valid(data)
                fh = open(self.path, "r+b")
                if not data.startswith(CORPUS_MAGIC):
                    fh.truncate(0)
                    fh.write(CORPUS_MAGIC)
                else:
                    fh.truncate(valid_end)
                    fh.seek(valid_end)
            else:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                fh = open(self.path, "w+b")
                fh.write(CORPUS_MAGIC)
            fh.flush()
        except OSError:
            # a read-only data dir costs durability, never the caller
            self.write_errors += 1
            return
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._fh = fh

    def close(self):
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    # -- writes ------------------------------------------------------------

    def append(self, records: list[dict]):
        """Append one frame per record; flush once.  Never raises."""
        if not records:
            return
        with self._lock:
            fh = self._fh
            if fh is None:
                return
            try:
                buf = b"".join(
                    _frame(json.dumps(r).encode()) for r in records)
                fh.write(buf)
                fh.flush()
                self.frames_appended += len(records)
            except (OSError, ValueError, TypeError):
                self.write_errors += 1

    def compact(self, records: list[dict]):
        """Atomically rewrite the log to exactly ``records``:
        tmp + fsync + rename so a crash mid-compaction leaves either
        the old log or the new one, never a hybrid.  Never raises."""
        tmp = self.path + ".compact"
        try:
            with open(tmp, "wb") as f:
                f.write(CORPUS_MAGIC)
                for r in records:
                    f.write(_frame(json.dumps(r).encode()))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except (OSError, ValueError, TypeError):
            self.write_errors += 1
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        # swap the append handle onto the new file
        self.open()
        with self._lock:
            self.frames_appended = len(records)

    # -- reads -------------------------------------------------------------

    @staticmethod
    def read(path: str) -> list[dict]:
        """Raw records in the valid frame prefix, append order.  Stops
        at the first bad frame; a CRC-valid frame holding non-JSON (a
        writer bug, not corruption) is skipped.  Never raises."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return []
        out: list[dict] = []
        if not data.startswith(CORPUS_MAGIC):
            return out
        pos = len(CORPUS_MAGIC)
        while pos + _FRAME_HDR.size <= len(data):
            ln, crc = _FRAME_HDR.unpack_from(data, pos)
            end = pos + _FRAME_HDR.size + ln
            payload = data[pos + _FRAME_HDR.size: end]
            if end > len(data) or checksum(payload) != crc:
                break
            try:
                rec = json.loads(payload)
                if isinstance(rec, dict):
                    out.append(rec)
            except ValueError:
                pass
            pos = end
        return out

    @staticmethod
    def load(path: str) -> dict[tuple, dict]:
        """Folded view keyed by (index, template): the latest record per
        key wins (each frame is a full snapshot, not a delta).  Records
        with a mismatched schema version or missing required keys are
        dropped — a stale-schema corpus is a cold start, not a crash."""
        folded: dict[tuple, dict] = {}
        for rec in SignatureCorpus.read(path):
            try:
                if rec.get("v") != SCHEMA_VERSION:
                    continue
                index, template = rec["index"], rec["template"]
                query, hits = rec["query"], int(rec["hits"])
                if not (isinstance(index, str) and isinstance(template, str)
                        and isinstance(query, str)):
                    continue
            except (KeyError, TypeError, ValueError):
                continue
            folded[(index, template)] = rec
        return folded


def top_n(records, n: int) -> list[dict]:
    """The n records with the most traffic (hits, then recency) — the
    warmup replay order and the compaction survivor set."""
    ranked = sorted(records, key=lambda r: (int(r.get("hits", 0)),
                                            float(r.get("lastUsed", 0.0))),
                    reverse=True)
    return ranked[:max(int(n), 0)]


class CorpusRecorder:
    """In-memory (index, template) -> record accumulator fed by the
    executor's success paths, flushed to a SignatureCorpus periodically.

    The executor calls ``note_sig`` where a whole-query launch knows its
    program signature (staged on a thread-local — request execution is
    synchronous on the calling thread) and ``note`` at its success
    return sites.  ``flush`` joins the staged records against the
    compile registry's per-signature entries for the shape fingerprint
    and compile seconds, appends the dirty ones, and compacts when the
    log outgrows its survivor set."""

    # compact when the on-disk log holds this many times the survivor
    # set — bounds the log without compacting on every flush
    COMPACT_FACTOR = 8

    def __init__(self, keep_n: int = 128):
        self.keep_n = max(int(keep_n), 1)
        self._lock = make_lock("warmup-recorder")
        self._local = threading.local()
        self._records: dict[tuple, dict] = {}
        self._dirty: set = set()
        self.noted = 0

    # -- executor-facing hooks (hot path: one dict update) -----------------

    def note_sig(self, sig: str | None):
        self._local.sig = sig

    def note(self, index: str, qtext: str):
        """Fold one successfully served read-only string query.  Never
        raises — recording must not fail the query that fed it."""
        sig = getattr(self._local, "sig", None)
        self._local.sig = None
        try:
            from ..executor.prepared import fingerprint
            template, _ = fingerprint(qtext)
        # lint: allow(swallowed-exception) — a fingerprint failure on an
        # exotic query costs one corpus record, never the query itself
        except Exception:
            return
        key = (index, template)
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = {"v": SCHEMA_VERSION, "index": index,
                       "template": template, "query": qtext, "sig": "",
                       "fp": "", "hits": 0, "lastUsed": 0.0,
                       "compileS": 0.0}
                self._records[key] = rec
            rec["hits"] = int(rec["hits"]) + 1
            rec["lastUsed"] = round(_wall_stamp(), 3)
            rec["query"] = qtext
            if sig:
                rec["sig"] = sig
            self._dirty.add(key)
            self.noted += 1

    # -- lifecycle ---------------------------------------------------------

    def seed(self, folded: dict[tuple, dict]):
        """Carry hit counts across restarts: the loaded corpus becomes
        the starting state, so compaction ranks long-run traffic, not
        just this process's uptime."""
        with self._lock:
            for key, rec in folded.items():
                self._records.setdefault(key, dict(rec))

    def flush(self, corpus: SignatureCorpus):
        """Enrich dirty records from the compile registry, append them,
        compact if the log has outgrown its bound.  Never raises."""
        from ..utils.devobs import COMPILES
        with self._lock:
            dirty = [dict(self._records[k]) for k in self._dirty
                     if k in self._records]
            self._dirty.clear()
        if dirty:
            by_sig = {e["sig"]: e
                      for e in COMPILES.snapshot().get("entries", [])}
            for rec in dirty:
                e = by_sig.get(rec.get("sig"))
                if e is not None:
                    rec["fp"] = e.get("lastFingerprint", "")
                    rec["compileS"] = round(
                        float(e.get("totalCompileS", 0.0)), 4)
                with self._lock:
                    live = self._records.get((rec["index"],
                                              rec["template"]))
                    if live is not None:
                        live["fp"] = rec.get("fp", "")
                        live["compileS"] = rec.get("compileS", 0.0)
            corpus.append(dirty)
        if corpus.frames_appended > self.keep_n * self.COMPACT_FACTOR:
            with self._lock:
                records = [dict(r) for r in self._records.values()]
            corpus.compact(top_n(records, self.keep_n))

    def snapshot(self) -> dict:
        with self._lock:
            return {"templates": len(self._records), "noted": self.noted,
                    "dirty": len(self._dirty)}
