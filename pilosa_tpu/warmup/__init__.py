"""Warm-start subsystem (docs/warmup.md): persistent compile cache,
durable signature corpus, and AOT warmup to READY.

A restart today pays the full trace+compile bill per program signature;
the reference engine just reopens mmap'd fragments.  This package earns
the same property for an XLA-lowered engine in three layers:

* ``compile_cache`` — jax's on-disk persistent compilation cache wired
  under data-dir, size-bounded with LRU pruning;
* ``corpus`` — a CRC-framed durable log of what this process compiles
  (signature, shape fingerprint, params schema/template, traffic);
* ``replayer`` — the boot-time coordinator that replays the top-N
  corpus queries through the real compile paths before READY.
"""

from .compile_cache import cache_stats, configure, prune, resolve_dir
from .corpus import CorpusRecorder, SignatureCorpus, top_n
from .replayer import (PHASE_COLD, PHASE_READY, PHASE_WARMING,
                       WarmupCoordinator)

__all__ = [
    "cache_stats", "configure", "prune", "resolve_dir",
    "CorpusRecorder", "SignatureCorpus", "top_n",
    "PHASE_COLD", "PHASE_READY", "PHASE_WARMING", "WarmupCoordinator",
]
