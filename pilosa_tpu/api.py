"""API façade: every externally-reachable operation, validated against
cluster state (reference api.go:135-1330).

The HTTP layer wraps this and only this (http/handler.go:276 wraps *API);
nothing in the server package touches holder/executor directly.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from . import __version__
from .core import SHARD_WIDTH
from .executor import Executor
from .storage import FieldOptions, Holder
from .utils.locks import make_rlock
from .utils.stats import StatsClient

# Cluster states (cluster.go:47-50).
STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"

# Which API methods are allowed in which states (api.go:99 validAPIMethods).
_DEGRADED_OK = {
    "Query", "Schema", "Status", "Version", "Info", "GetIndex", "GetIndexes",
    "ExportCSV", "ShardNodes", "Hosts",
}
# Queries keep serving during a resize like the reference (reads route by
# the pre-resize placement; old owners retain their fragments until the
# deferred holder cleaner runs after the membership switch).  WRITE calls
# inside a query are rejected by the cluster layer while RESIZING — data
# in flight between owners cannot accept mutations exactly-once.
_RESIZING_OK = {"Query", "Schema", "Status", "Version", "Info", "GetIndex",
                "GetIndexes", "ShardNodes", "Hosts", "ClusterMessage"}


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class ConflictError(ApiError):
    pass


class DisallowedError(ApiError):
    """Method not allowed in current cluster state (api.go:119 validate)."""


class UnsupportedMediaTypeError(ApiError):
    """Request body format the handler does not accept — HTTP 415.  The
    capability-mismatch signal of internal query wire negotiation: a
    node pinned to internal-wire=json answers binary /internal/query
    POSTs with it, and the calling InternalClient downgrades that peer
    to the JSON wire (docs/cluster.md "Internal query wire")."""


class API:
    def __init__(self, holder: Holder, cluster=None, stats=None,
                 use_mesh: bool = True, dispatch_batch: bool = True,
                 dispatch_batch_max: int = 32,
                 dispatch_batch_window_us: float = 200.0,
                 whole_query: bool = True,
                 whole_query_fallback: str = "legacy"):
        """``use_mesh=True`` (the default, config-gated by the server)
        executes served queries over the device mesh — stacked shard
        batches under shard_map with ICI reductions — the production
        equivalent of the reference's worker pool + mapReduce
        (executor.go:80-110, 2455).  ``dispatch_batch*``: cross-query
        dynamic batching of device dispatch (docs/batching.md).
        ``whole_query``: compile each read request into ONE pjit
        program over the mesh (docs/whole-query.md)."""
        self.holder = holder
        self.cluster = cluster  # None = single-node
        self.stats = stats if stats is not None else StatsClient()
        # Warm-start coordinator (warmup/replayer.py), injected by the
        # Server; None (bare API) means no warming phase — /status
        # reports READY immediately, the pre-warmup behavior.
        self.warmup = None
        self.executor = Executor(
            holder, use_mesh=use_mesh, stats=self.stats,
            dispatch_batch=dispatch_batch,
            dispatch_batch_max=dispatch_batch_max,
            dispatch_batch_window_us=dispatch_batch_window_us,
            whole_query=whole_query,
            whole_query_fallback=whole_query_fallback)
        self._lock = make_rlock("api-schema")

    # -- state validation (api.go:119) -------------------------------------

    def state(self) -> str:
        if self.cluster is None:
            return STATE_NORMAL
        return self.cluster.state

    def _validate(self, method: str):
        st = self.state()
        if st == STATE_NORMAL:
            return
        if st == STATE_DEGRADED and method in _DEGRADED_OK:
            return
        if st == STATE_RESIZING and method in _RESIZING_OK:
            return
        raise DisallowedError(
            f"api method {method} not allowed in state {st}")

    # -- query (api.go:135 Query) ------------------------------------------

    def query(self, index: str, query: str, shards=None,
              ctx=None) -> list[Any]:
        """``ctx``: optional QueryContext carrying the query's deadline
        (utils/deadline.py); defaults to the caller's active context (the
        HTTP handler installs one from ?timeout= / the deadline header /
        the query-timeout config default)."""
        self._validate("Query")
        if self.stats:
            self.stats.count("query", 1)
        from .utils.deadline import current
        if ctx is None:
            ctx = current()
        from .utils import profile as qprof
        from .utils.tracing import GLOBAL_TRACER
        with GLOBAL_TRACER.span("api.Query") as span:
            span.set_tag("index", index)
            prof = qprof.current()
            if prof is not None:
                # root tags of the EXPLAIN ANALYZE tree: the index and
                # the trace id the stages correlate to
                prof.tag("index", index)
                prof.tag("traceID", span.trace_id)
            if self.cluster is not None:
                return self.cluster.execute(index, query, shards, ctx=ctx)
            return self.executor.execute(index, query, shards, ctx=ctx)

    # -- DDL ---------------------------------------------------------------

    def _broadcast(self, msg: dict):
        """Schema changes propagate to every node synchronously
        (api.go:233 CreateField -> SendSync, broadcast.go:30)."""
        if self.cluster is not None:
            self.cluster.broadcast(msg)

    def create_index(self, name: str, keys: bool = False,
                     track_existence: bool = True):
        self._validate("CreateIndex")
        try:
            idx = self.holder.create_index(name, keys=keys,
                                           track_existence=track_existence)
        except FileExistsError as e:
            raise ConflictError(str(e))
        except ValueError as e:
            raise ApiError(str(e))
        self._broadcast({"type": "create-index", "index": name,
                         "keys": keys, "trackExistence": track_existence})
        return idx

    def delete_index(self, name: str):
        self._validate("DeleteIndex")
        try:
            self.holder.delete_index(name)
        except ValueError as e:
            raise NotFoundError(str(e))
        if self.cluster is not None:
            self.cluster.forget_index_shards(name)
        self._broadcast({"type": "delete-index", "index": name})

    def create_field(self, index: str, field: str,
                     options: dict | None = None):
        self._validate("CreateField")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        try:
            # from_dict validates cacheType/cacheSize (FieldOptions
            # __post_init__) — bad options must 400, not 500
            opts = FieldOptions.from_dict(options or {})
            f = idx.create_field(field, opts)
        except FileExistsError as e:
            raise ConflictError(str(e))
        except ValueError as e:
            raise ApiError(str(e))
        self._broadcast({"type": "create-field", "index": index,
                         "field": field, "options": options or {}})
        return f

    def delete_field(self, index: str, field: str):
        self._validate("DeleteField")
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        try:
            idx.delete_field(field)
        except ValueError as e:
            raise NotFoundError(str(e))
        self._broadcast({"type": "delete-field", "index": index,
                         "field": field})

    def schema(self) -> list[dict]:
        self._validate("Schema")
        return self.holder.schema()

    def apply_schema(self, schema: list[dict]):
        """POST /schema (http/handler.go handlePostSchema)."""
        self._validate("ApplySchema")
        for idx_def in schema:
            name = idx_def["name"]
            opts = idx_def.get("options", {})
            idx = self.holder.create_index_if_not_exists(
                name, keys=opts.get("keys", False),
                track_existence=opts.get("trackExistence", True))
            self._broadcast({"type": "create-index", "index": name,
                             "keys": opts.get("keys", False),
                             "trackExistence": opts.get("trackExistence",
                                                        True)})
            for fdef in idx_def.get("fields", []):
                idx.create_field_if_not_exists(
                    fdef["name"], FieldOptions.from_dict(
                        fdef.get("options", {})))
                self._broadcast({"type": "create-field", "index": name,
                                 "field": fdef["name"],
                                 "options": fdef.get("options", {})})

    # -- import (api.go:920 Import / :1031 ImportValue / :368 ImportRoaring)

    def _translate_import_keys(self, idx, f, row_keys, column_keys,
                               row_ids, column_ids):
        """Key->id translation at the head of the import pipeline
        (api.go:926-961)."""
        if column_keys is not None:
            if not idx.keys:
                raise ApiError(
                    "columnKeys not allowed: index 'keys' option disabled")
            column_ids = idx.translate_store().translate_keys(column_keys)
        if row_keys is not None:
            if not f.options.keys:
                raise ApiError(
                    "rowKeys not allowed: field 'keys' option disabled")
            row_ids = f.translate_store().translate_keys(row_keys)
        return row_ids, column_ids

    def import_bits(self, index: str, field: str,
                    row_ids=None, column_ids=None, timestamps=None,
                    clear: bool = False, row_keys=None, column_keys=None):
        self._validate("Import")
        idx, f = self._index_field(index, field)
        row_ids, column_ids = self._translate_import_keys(
            idx, f, row_keys, column_keys, row_ids, column_ids)
        rows = np.asarray(row_ids or [], dtype=np.int64)
        cols = np.asarray(column_ids or [], dtype=np.int64)
        if rows.size != cols.size:
            raise ApiError("rowIDs and columnIDs length mismatch")
        if timestamps and len(timestamps) != cols.size:
            raise ApiError("timestamps length mismatch")
        if self.cluster is not None:
            # regroup by shard, forward each batch to its owners
            # (api.go:963-996)
            self.cluster.import_bits(index, field, rows, cols, timestamps,
                                     clear=clear)
            return
        self._import_bits_local(idx, f, rows, cols, timestamps, clear)

    @staticmethod
    def _import_bits_local(idx, f, rows, cols, timestamps, clear):
        ts = None
        if timestamps:
            from datetime import datetime, timezone
            ts = [None if t in (None, 0)
                  else datetime.fromtimestamp(t, timezone.utc)
                  .replace(tzinfo=None)
                  for t in timestamps]
        f.import_bits(rows, cols, ts, clear=clear)
        if not clear:
            idx.add_existence(cols)

    def import_values(self, index: str, field: str,
                      column_ids=None, values=None, clear: bool = False,
                      column_keys=None):
        self._validate("ImportValue")
        idx, f = self._index_field(index, field)
        _, column_ids = self._translate_import_keys(
            idx, f, None, column_keys, None, column_ids)
        cols = np.asarray(column_ids or [], dtype=np.int64)
        vals = np.asarray(values or [], dtype=np.int64)
        if not clear and cols.size != vals.size:
            raise ApiError("columnIDs and values length mismatch")
        if self.cluster is not None:
            self.cluster.import_values(index, field, cols, vals, clear=clear)
            return
        f.import_values(cols, vals, clear=clear)
        if not clear:
            idx.add_existence(cols)

    def apply_import_local(self, index: str, field: str, payload: dict):
        """Apply a forwarded (pre-grouped) import batch locally — the
        receive side of the cluster import fan-out; never re-forwards."""
        idx, f = self._index_field(index, field)
        if "values" in payload and payload.get("values") is not None:
            cols = np.asarray(payload.get("columnIDs") or [], dtype=np.int64)
            vals = np.asarray(payload["values"], dtype=np.int64)
            f.import_values(cols, vals, clear=payload.get("clear", False))
            if not payload.get("clear", False):
                idx.add_existence(cols)
            return
        rows = np.asarray(payload.get("rowIDs") or [], dtype=np.int64)
        cols = np.asarray(payload.get("columnIDs") or [], dtype=np.int64)
        if payload.get("clear", False) and "rowIDs" not in payload:
            f.import_values(cols, np.zeros(0, dtype=np.int64), clear=True)
            return
        self._import_bits_local(idx, f, rows, cols,
                                payload.get("timestamps"),
                                payload.get("clear", False))

    def check_ingest(self, index: str, field: str) -> str:
        """Validation head of the streaming ingest path (docs/ingest.md):
        cluster-state gate + index/field existence.  The committer
        applies records asynchronously, so unknown names must 404 at the
        socket before any frame is read, not at flush time.  Returns the
        field type so the handler can reject mismatched record types
        (values frames at a set field and vice versa) per frame."""
        self._validate("Import")
        _idx, f = self._index_field(index, field)
        return f.options.type

    def import_roaring(self, index: str, field: str, shard: int,
                       views: dict[str, bytes], clear: bool = False):
        """Import pre-serialized pilosa-roaring bitmaps, one per view
        (api.go:368 ImportRoaring)."""
        self._validate("ImportRoaring")
        if self.cluster is not None:
            self.cluster.import_roaring(index, field, shard, views, clear)
            return
        self.apply_import_roaring_local(index, field, shard, views, clear)

    def apply_import_roaring_local(self, index: str, field: str, shard: int,
                                   views: dict[str, bytes],
                                   clear: bool = False):
        idx, f = self._index_field(index, field)
        from .storage.roaring_io import unpack_roaring
        all_cols = []
        for view_name, data in views.items():
            if not view_name:
                view_name = "standard"
            rows, cols_local = unpack_roaring(data, self.holder.max_row_id)
            v = f._create_view_if_not_exists(view_name)
            frag = v.create_fragment_if_not_exists(shard)
            if clear:
                frag.bulk_import(rows, cols_local, clear=True)
            else:
                frag.bulk_import(rows, cols_local)
                if view_name == "standard":
                    all_cols.append(cols_local + shard * SHARD_WIDTH)
        if all_cols:
            idx.add_existence(np.unique(np.concatenate(all_cols)))

    def _index_field(self, index: str, field: str):
        idx = self.holder.index(index)
        if idx is None:
            raise NotFoundError(f"index not found: {index}")
        f = idx.field(field)
        if f is None:
            raise NotFoundError(f"field not found: {field}")
        return idx, f

    # -- export (api.go ExportCSV) -----------------------------------------

    def export_csv(self, index: str, field: str, shard: int) -> str:
        self._validate("ExportCSV")
        _, f = self._index_field(index, field)
        from .core import VIEW_STANDARD
        v = f.view(VIEW_STANDARD)
        frag = None if v is None else v.fragment(shard)
        if frag is None:
            return ""
        from .ops import bitset
        rows, cols = bitset.unpack_fragment(frag.words)
        offset = shard * SHARD_WIDTH
        return "".join(f"{r},{c + offset}\n" for r, c in zip(rows, cols))

    # -- info/status -------------------------------------------------------

    def status(self) -> dict:
        self._validate("Status")
        # warm-start phase (docs/warmup.md): while the AOT replayer is
        # warming, this node advertises WARMING — peers' probe folds and
        # read routers treat it as not-READY, so no traffic lands on a
        # cold process; clustered nodes ALSO carry it in their local
        # node state (the Server flips it at warmup completion)
        warming = self.warmup is not None and self.warmup.warming()
        nodes = [{"id": "node0", "uri": "", "isCoordinator": True,
                  "state": "WARMING" if warming else "READY"}]
        state = STATE_NORMAL
        epoch = 0
        out = {}
        if self.cluster is not None:
            nodes = self.cluster.node_statuses()
            state = self.cluster.state
            epoch = self.cluster.epoch
            # per-index fragment-gen summaries ride the health probes so
            # peers' result caches see out-of-band writes within one
            # probe interval (cache/results.py gen_summary)
            from .cache.results import gen_summary
            out["dataGens"] = {
                name: list(gen_summary(self.holder, name))
                for name in list(self.holder.indexes)}
            # elastic-serving piggybacks (parallel/routing.py): admission
            # depth + per-shard residency tiers ride the health probes so
            # peers' read routers score this node without extra RPCs, and
            # the overlay epoch lets the coordinator re-push a missed
            # placement-overlay broadcast (docs/cluster.md)
            out["load"] = self.cluster.local_load()
            out["residency"] = self.cluster.residency_summary()
            out["overlayEpoch"] = self.cluster.overlay_epoch
            # internal-query wire capability advertisement: peers' probe
            # folds feed this to their InternalClient negotiation
            # (docs/cluster.md "Internal query wire")
            out["wire"] = self.cluster.wire_capabilities()
        out.update({"state": state, "nodes": nodes, "epoch": epoch,
                    "localID": nodes[0]["id"] if self.cluster is None
                    else self.cluster.node_id})
        # Storage health: quarantined fragments degrade this node (empty
        # reads + refused writes on those fragments) but do NOT take it
        # down — replica repair heals them while everything else serves.
        quarantined = self.holder.quarantined_fragments()
        out["storage"] = {
            "quarantinedFragments": len(quarantined),
            "degraded": bool(quarantined),
        }
        out["warming"] = warming
        out["phase"] = "warming" if warming else "ready"
        if self.warmup is not None:
            out["warmup"] = self.warmup.status()
        return out

    def info(self) -> dict:
        self._validate("Info")
        return {"shardWidth": SHARD_WIDTH}

    def version(self) -> str:
        return __version__

    def max_shards(self) -> dict[str, int]:
        """(api.go MaxShards, /internal/shards/max).  Cluster-wide: a
        node answering for shards it doesn't own must still report them
        (the export CLI walks 0..max and routes each shard to an owner)."""
        if self.cluster is not None:
            return {name: max(self.cluster._available_shards(
                                  name, mark_down=False), default=0)
                    for name in list(self.holder.indexes)}
        return {name: max(idx.available_shards(), default=0)
                for name, idx in self.holder.indexes.items()}

    def shard_nodes(self, index: str, shard: int) -> list[dict]:
        self._validate("ShardNodes")
        if self.cluster is None:
            return [{"id": "node0", "uri": ""}]
        return self.cluster.shard_nodes_info(index, shard)

    def recalculate_caches(self):
        """(api.go RecalculateCaches): eagerly rebuild every fragment's
        rank cache so the next TopN doesn't pay the lazy rebuild.

        Rebuilds run as BACKGROUND work through the dispatch batcher
        (docs/batching.md): between fragments the loop yields while
        foreground tickets are queued, so a holder-wide recalculation
        can't starve live queries of the dispatcher (or the GIL) while
        it walks every fragment's sparse store."""
        self._validate("RecalculateCaches")
        from .cache.rank import iter_rank_caches
        from contextlib import nullcontext
        batcher = self.executor.batcher
        bg = batcher.background() if batcher is not None else nullcontext()
        with bg:
            for frag, cache in iter_rank_caches(self.holder):
                if batcher is not None:
                    batcher.yield_to_foreground()
                with frag._lock:
                    cache.build(frag)
        return None
