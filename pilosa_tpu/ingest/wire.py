"""Ingest wire format: length-prefixed CRC-framed binary record streams.

The JSON import surface parses every row id through a Python dict; at
millions of events per second the parse IS the bottleneck (and base64
roaring bodies pay a 4/3 blowup on top).  The ingest endpoint speaks a
binary stream instead, built from the same two primitives as the framed
WAL (storage/fragment.py): an 8-byte magic, then frames of

    <u32 payload_len, u32 payload_crc> payload

where ``payload_crc`` is ``utils.durable.checksum`` (zlib crc32) over the
payload and the payload is one record-type byte followed by fixed-width
packed records:

    type 0  "bits"       <i64 row, i64 col>            set bits
    type 1  "bits+ts"    <i64 row, i64 col, i64 ts>    timestamped set
                         bits (ts = unix seconds; 0 = untimed)
    type 2  "values"     <i64 col, i64 value>          BSI int values

Columns are GLOBAL column ids — the server routes each record to its
shard's owners via the cluster placement.  A frame is the unit of
acknowledgement: the server's 200 response means every frame it read was
group-committed to the WAL (docs/ingest.md).  Frames are idempotent (set
bits / last-write-wins values), so a client that got a 503 or lost the
connection mid-stream can safely resend the whole stream.

Numpy record-dtype views keep encode and decode a single memcpy-shaped
operation per frame — no per-record Python loop on either side.
"""

from __future__ import annotations

import struct

import numpy as np

from ..utils.durable import checksum

MAGIC = b"PTPUING1"
FRAME = struct.Struct("<II")

REC_BITS = 0
REC_BITS_TS = 1
REC_VALS = 2

# fixed record layouts per type (little-endian, like the WAL)
_DTYPES = {
    REC_BITS: np.dtype([("row", "<i8"), ("col", "<i8")]),
    REC_BITS_TS: np.dtype([("row", "<i8"), ("col", "<i8"), ("ts", "<i8")]),
    REC_VALS: np.dtype([("col", "<i8"), ("value", "<i8")]),
}

# Server-side per-frame ceiling (ingest-max-frame-mb overrides): a frame
# must be buffered whole for its CRC, so it bounds per-connection memory.
DEFAULT_MAX_FRAME_BYTES = 32 << 20


class FrameError(ValueError):
    """Malformed ingest stream (bad magic, CRC mismatch, bad record
    type, oversized or truncated frame).  The server answers 400 and
    closes the connection — mid-stream garbage cannot be resynced."""


def pack_bits(rows, cols, ts=None) -> bytes:
    """Pack (row, col[, ts]) arrays into one frame payload."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    rectype = REC_BITS if ts is None else REC_BITS_TS
    recs = np.empty(rows.size, dtype=_DTYPES[rectype])
    recs["row"] = rows
    recs["col"] = cols
    if ts is not None:
        recs["ts"] = np.asarray(ts, dtype=np.int64)
    return bytes([rectype]) + recs.tobytes()


def pack_values(cols, values) -> bytes:
    """Pack (col, value) arrays into one REC_VALS frame payload."""
    cols = np.asarray(cols, dtype=np.int64)
    recs = np.empty(cols.size, dtype=_DTYPES[REC_VALS])
    recs["col"] = cols
    recs["value"] = np.asarray(values, dtype=np.int64)
    return bytes([REC_VALS]) + recs.tobytes()


def encode_frame(payload: bytes) -> bytes:
    """One framed payload (no magic — the stream carries it once)."""
    return FRAME.pack(len(payload), checksum(payload)) + payload


def encode_records(rows, cols, ts=None, values=None,
                   frame_records: int = 65536,
                   magic: bool = True) -> bytes:
    """Whole-stream convenience encoder (clients, tests, the bench):
    magic + records split into frames of at most ``frame_records``."""
    out = [MAGIC] if magic else []
    n = len(cols)
    for lo in range(0, max(n, 1), frame_records):
        hi = min(lo + frame_records, n)
        if hi <= lo:
            break
        if values is not None:
            payload = pack_values(cols[lo:hi], values[lo:hi])
        else:
            payload = pack_bits(rows[lo:hi], cols[lo:hi],
                                None if ts is None else ts[lo:hi])
        out.append(encode_frame(payload))
    return b"".join(out)


def decode_payload(payload: bytes) -> tuple[int, np.ndarray]:
    """(record type, structured record array) of one verified payload."""
    if not payload:
        raise FrameError("empty ingest frame")
    rectype = payload[0]
    dt = _DTYPES.get(rectype)
    if dt is None:
        raise FrameError(f"unknown ingest record type {rectype}")
    body = payload[1:]
    if len(body) % dt.itemsize:
        raise FrameError(
            f"ingest frame length {len(body)} is not a multiple of the "
            f"type-{rectype} record size {dt.itemsize}")
    return rectype, np.frombuffer(body, dtype=dt)


class FrameReader:
    """Incremental frame parser over a ``read(n)`` source (the HTTP
    request's rfile).  Reads AT MOST ``limit`` total bytes (the request's
    Content-Length) and never buffers more than one frame — the server
    must not materialise a multi-GB stream to parse it.

    ``next_frame()`` returns ``(rectype, records, frame_bytes)`` or
    ``None`` at the end of the stream; malformed input raises
    ``FrameError``."""

    def __init__(self, read, limit: int,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self._read = read
        self.remaining = limit
        self.max_frame_bytes = max_frame_bytes
        self._magic_read = False

    def _read_exact(self, n: int) -> bytes:
        if n > self.remaining:
            raise FrameError("ingest stream truncated (frame runs past "
                             "Content-Length)")
        chunks = []
        got = 0
        while got < n:
            chunk = self._read(min(n - got, 1 << 20))
            if not chunk:
                raise FrameError("ingest stream truncated (connection "
                                 "closed mid-frame)")
            chunks.append(chunk)
            got += len(chunk)
        self.remaining -= n
        return b"".join(chunks)

    def next_frame(self):
        if not self._magic_read:
            if self.remaining < len(MAGIC):
                raise FrameError("ingest stream shorter than its magic")
            if self._read_exact(len(MAGIC)) != MAGIC:
                raise FrameError(
                    f"bad ingest stream magic (expected {MAGIC!r})")
            self._magic_read = True
        if self.remaining == 0:
            return None
        if self.remaining < FRAME.size:
            raise FrameError("truncated ingest frame header")
        plen, crc = FRAME.unpack(self._read_exact(FRAME.size))
        if plen == 0 or plen > self.max_frame_bytes:
            raise FrameError(
                f"ingest frame of {plen} bytes outside (0, "
                f"{self.max_frame_bytes}] (ingest-max-frame-mb)")
        payload = self._read_exact(plen)
        if checksum(payload) != crc:
            raise FrameError("ingest frame CRC mismatch")
        rectype, recs = decode_payload(payload)
        return rectype, recs, FRAME.size + plen
