"""Streaming ingest subsystem (docs/ingest.md).

The production write path: length-prefixed binary frames off the socket
(``wire``), per-fragment group commit — one WAL frame, one generation
bump, one rank-cache touch per flush, not per request (``committer``) —
and HBM delta overlays so freshly ingested bits reach queries without
re-staging whole fragments (``delta`` + parallel/mesh_exec.py).
"""

from .committer import GroupCommitter
from .wire import (FrameError, FrameReader, MAGIC, REC_BITS, REC_BITS_TS,
                   REC_VALS, encode_frame, encode_records, pack_bits,
                   pack_values)

__all__ = [
    "GroupCommitter", "FrameError", "FrameReader", "MAGIC",
    "REC_BITS", "REC_BITS_TS", "REC_VALS",
    "encode_frame", "encode_records", "pack_bits", "pack_values",
]
