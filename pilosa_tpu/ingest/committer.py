"""Group commit for the streaming ingest path (docs/ingest.md).

Every ingest request used to become one ``bulk_import`` per fragment per
HTTP call: one WAL frame, one generation bump, and one rank-cache
recount EACH — at millions of events/sec the per-call bookkeeping, not
the bit merge, is the write ceiling.  The committer accumulates records
across requests (and across concurrent connections) and flushes them in
batches: one flush = one ``Field.ingest_import`` per touched field = ONE
WAL frame + ONE gen bump + ONE rank-cache touch per fragment, riding the
CRC-framed WAL append that PR 6 built as exactly this group-commit unit
(storage/fragment.py ``_log_ops``).

Acknowledgement contract: ``submit`` only records; the HTTP handler acks
its response AFTER ``wait_flushed`` returns for the last submitted
sequence — i.e. a 200 means every frame of the request hit the WAL (the
kill -9 harness in tests/test_ingest.py holds this to zero acked-frame
loss).  Flushes trigger on pending bytes, pending records, or the
``ingest-flush-ms`` timer, whichever first.

Backpressure: ``wait_capacity`` blocks admission of further frames while
the unflushed backlog exceeds its high-water mark, so a slow device
merge propagates to the socket as a bounded wait and then a 503 +
Retry-After (handler).  The flush loop is also the subsystem's only
cross-fragment journal folder (the "background merge"): it folds
fragments when the process-wide delta budget
(membudget.INGEST_DELTA_LIMIT_BYTES) runs over, and retires journals
that have gone idle for several flushes — in batches, never per bit.
"""

from __future__ import annotations

import threading
import time
from datetime import datetime, timezone

import numpy as np

from ..core import SHARD_WIDTH, VIEW_STANDARD
from ..storage import membudget as _membudget
from ..utils.faults import FAULTS
from ..utils.locks import make_condition, make_lock


class _Pending:
    __slots__ = ("rows", "cols", "ts", "values", "nbytes")

    def __init__(self):
        self.rows: list = []
        self.cols: list = []
        self.ts: list = []
        self.values: list = []
        self.nbytes = 0


class GroupCommitter:
    """One per server.  ``flush_ms <= 0`` flushes synchronously inside
    ``wait_flushed`` (no background thread — tests and tiny tools)."""

    # flush when the pending batch crosses either threshold, without
    # waiting out the timer
    FLUSH_BYTES = 8 << 20
    FLUSH_RECORDS = 1 << 18
    # backlog high-water: wait_capacity blocks above this
    HIGH_WATER_BYTES = 32 << 20
    # flush cycles a fragment's journal may sit idle before the merge
    # pass folds it (bounds how long queries pay the overlay OR)
    MERGE_IDLE_FLUSHES = 16

    def __init__(self, holder, flush_ms: float = 50.0, stats=None,
                 flush_bytes: int | None = None,
                 flush_records: int | None = None,
                 high_water_bytes: int | None = None):
        self.holder = holder
        self.flush_ms = flush_ms
        self.stats = stats
        if flush_bytes is not None:
            self.FLUSH_BYTES = flush_bytes
        if flush_records is not None:
            self.FLUSH_RECORDS = flush_records
        if high_water_bytes is not None:
            self.HIGH_WATER_BYTES = high_water_bytes
        self._cond = make_condition("committer")
        # Serializes whole flushes (take -> apply -> ack).  Without it,
        # two inline-mode (flush_ms <= 0) callers could interleave: the
        # second takes an EMPTY pending set stamped with the first's
        # covering sequence and advances _flushed_seq before the first
        # has written its WAL frames — acking undurable data.
        self._flush_lock = make_lock("committer-flush")
        self._pend: dict[tuple[str, str], _Pending] = {}
        self._pend_bytes = 0
        self._pend_records = 0
        self._submit_seq = 0     # last sequence handed out
        self._flushed_seq = 0    # last sequence covered by a flush
        self._flush_no = 0
        # covering seq -> (seq the previous flush covered, error): an
        # error is attributed to the (start, end] submission range its
        # flush actually applied, so a producer whose records an EARLIER
        # flush committed never sees a later flush's failure
        self._flush_errors: dict[int, tuple[int, Exception]] = {}
        # fragments with live overlay journals -> last flush_no touched
        self._journal_frags: dict = {}
        self._closing = False
        self._thread = None
        # lifetime counters (snapshot() -> /debug/vars ingest section)
        self.flushes = 0
        self.records_total = 0
        self.folds = 0
        # backpressure episode latch: engage journaled at the first
        # refusal, release at the flush that drains under the mark
        self._backpressure = False

    def _ensure_thread(self):
        if self._thread is None and self.flush_ms > 0:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="ptpu-ingest-commit")
            self._thread.start()

    # -- producer side -----------------------------------------------------

    def submit(self, index: str, field: str, rows=None, cols=None,
               ts=None, values=None) -> int:
        """Record a batch for the next flush; returns the sequence the
        caller must ``wait_flushed`` on before acking."""
        cols = np.asarray(cols, dtype=np.int64)
        with self._cond:
            if self._closing:
                raise RuntimeError("ingest committer is closed")
            p = self._pend.setdefault((index, field), _Pending())
            nbytes = int(cols.nbytes)
            p.cols.append(cols)
            if values is not None:
                values = np.asarray(values, dtype=np.int64)
                p.values.append(values)
                nbytes += int(values.nbytes)
            else:
                rows = np.asarray(rows, dtype=np.int64)
                p.rows.append(rows)
                nbytes += int(rows.nbytes)
                # ts always appended (zeros = untimed) so the flush's
                # concatenation stays aligned with rows across batches
                # that mix timed and untimed records
                if ts is not None:
                    ts = np.asarray(ts, dtype=np.int64)
                else:
                    ts = np.zeros(rows.size, dtype=np.int64)
                p.ts.append(ts)
                nbytes += int(ts.nbytes)
            p.nbytes += nbytes
            self._pend_bytes += nbytes
            self._pend_records += int(cols.size)
            self._submit_seq += 1
            seq = self._submit_seq
            if self._pend_bytes >= self.FLUSH_BYTES or \
                    self._pend_records >= self.FLUSH_RECORDS:
                self._cond.notify_all()  # wake the flusher early
            self._ensure_thread()
            return seq

    def wait_flushed(self, seq: int, timeout: float | None = 30.0) -> bool:
        """Block until a flush covering ``seq`` completed; raises the
        flush's error if applying it failed (the producer must NOT ack).
        With no flusher thread (flush_ms <= 0) this flushes inline."""
        if self.flush_ms <= 0:
            self._flush_once()
        with self._cond:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while self._flushed_seq < seq:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cond.notify_all()
                self._cond.wait(0.05 if left is None else min(left, 0.05))
            err = None
            for fseq, (start, e) in self._flush_errors.items():
                if start < seq <= fseq:
                    err = e
            if err is not None:
                raise err
            return True

    def pending_bytes(self) -> int:
        with self._cond:
            return self._pend_bytes

    def wait_capacity(self, timeout: float = 0.5) -> bool:
        """Backpressure gate: True when the unflushed backlog is under
        the high-water mark (possibly after waiting for a flush), False
        when the producer should be rejected with 503 + Retry-After.
        The ENGAGE transition (first refusal of a backpressure episode)
        is journaled; the matching RELEASE is journaled by the flush
        that drains the backlog back under the mark."""
        deadline = time.monotonic() + timeout
        engaged = False
        with self._cond:
            while self._pend_bytes >= self.HIGH_WATER_BYTES:
                self._cond.notify_all()
                left = deadline - time.monotonic()
                if left <= 0 or self.flush_ms <= 0:
                    if not self._backpressure:
                        self._backpressure = True
                        engaged = True
                    break
                self._cond.wait(min(left, 0.05))
            else:
                return True
        if engaged:
            from ..utils import events
            events.emit("ingest.backpressure_engage",
                        backlogBytes=self._pend_bytes,
                        highWaterBytes=self.HIGH_WATER_BYTES)
        return False

    # -- flusher side ------------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                if self._closing and not self._pend:
                    return
                # group window: submits coalesce for up to flush_ms (a
                # threshold crossing or a parked wait_flushed producer
                # notifies early — classic group commit)
                if not (self._closing
                        or self._pend_bytes >= self.FLUSH_BYTES
                        or self._pend_records >= self.FLUSH_RECORDS):
                    self._cond.wait(self.flush_ms / 1e3)
            try:
                self._flush_once()
            # lint: allow(swallowed-exception) — per-flush errors are
            # recorded per covering sequence inside _flush_once and
            # re-raised to every waiter in its submission range
            except Exception:
                pass

    def _take_pending(self):
        with self._cond:
            pend, self._pend = self._pend, {}
            seq = self._submit_seq
            self._pend_bytes = 0
            self._pend_records = 0
        return pend, seq

    def _flush_once(self):
        with self._flush_lock:
            self._flush_once_locked()

    def _flush_once_locked(self):
        t0 = time.perf_counter()
        start_seq = self._flushed_seq
        pend, seq = self._take_pending()
        if pend:
            # crash window BEFORE any WAL append of this flush: a kill
            # here loses only unacked frames (tests/test_ingest.py)
            FAULTS.hit("ingest.flush", key=str(self._flush_no))
        err: Exception | None = None
        n_records = 0
        touched: list = []
        for (index, field), p in pend.items():
            try:
                touched.extend(self._apply(index, field, p))
                n_records += sum(int(c.size) for c in p.cols)
            except Exception as e:  # quarantine, validation, deleted field
                err = e
        if pend:
            # crash window AFTER the WAL appends, BEFORE ackers release:
            # data is durable but never acked — allowed (idempotent)
            FAULTS.hit("ingest.flush.ack", key=str(self._flush_no))
        with self._cond:
            if pend:
                # _flush_no counts DATA flushes only: the merge-idle
                # policy is "N flushes of OTHER data since this journal
                # was touched", not wall-clock timer ticks — an idle
                # server must not fold (and force restacks for)
                # journals nothing has superseded
                self._flush_no += 1
                self.flushes += 1
                self.records_total += n_records
            if err is not None and seq > start_seq:
                self._flush_errors[seq] = (start_seq, err)
                if len(self._flush_errors) > 64:
                    self._flush_errors.pop(next(iter(self._flush_errors)))
            self._flushed_seq = max(self._flushed_seq, seq)
            for frag in touched:
                self._journal_frags[frag] = self._flush_no
            released = self._backpressure \
                and self._pend_bytes < self.HIGH_WATER_BYTES
            if released:
                self._backpressure = False
            self._cond.notify_all()
        if released:
            from ..utils import events
            events.emit("ingest.backpressure_release",
                        backlogBytes=self.pending_bytes())
        if pend and self.stats is not None:
            self.stats.timing("ingest.flush", time.perf_counter() - t0)
            self.stats.count("ingest.flushes")
        self._merge_pass()

    def _apply(self, index: str, field: str, p: _Pending) -> list:
        """One field's flush batch -> one grouped import; returns the
        fragments that now hold overlay journals (merge-pass tracking)."""
        idx = self.holder.index(index)
        f = idx.field(field) if idx is not None else None
        if f is None:
            raise ValueError(f"ingest: unknown field {index}/{field}")
        cols = np.concatenate(p.cols)
        if p.values:
            f.import_values(cols, np.concatenate(p.values))
            idx.add_existence(np.unique(cols))
            return []
        rows = np.concatenate(p.rows)
        ts_list = None
        if p.ts and f.options.time_quantum:
            ts_arr = np.concatenate(p.ts)
            if np.any(ts_arr != 0):
                ts_list = [None if t == 0 else
                           datetime.fromtimestamp(int(t), timezone.utc)
                           .replace(tzinfo=None) for t in ts_arr]
        f.ingest_import(rows, cols, ts_list)
        idx.add_existence(np.unique(cols))
        out = []
        v = f.view(VIEW_STANDARD)
        if v is not None:
            for shard in np.unique(cols // SHARD_WIDTH):
                frag = v.fragment(int(shard))
                if frag is not None and frag.delta_bytes() > 0:
                    out.append(frag)
        return out

    def _merge_pass(self):
        """Background merge, in batches: fold overlay journals when the
        process-wide delta budget runs over (coldest first) or when a
        journal has sat idle for MERGE_IDLE_FLUSHES flushes.  This is
        the ONLY cross-fragment folder — single-threaded, taking one
        fragment lock at a time, so folding can never order fragment
        locks against each other."""
        with self._cond:
            frags = list(self._journal_frags.items())
            flush_no = self._flush_no
        limit = _membudget.INGEST_DELTA_LIMIT_BYTES
        over = limit > 0 and \
            _membudget.INGEST_DELTA_BUDGET.resident_bytes > limit
        folded = []
        for frag, last in sorted(frags, key=lambda kv: kv[1]):
            idle = flush_no - last >= self.MERGE_IDLE_FLUSHES
            if not (over or idle):
                continue
            if frag.fold_delta():
                self.folds += 1
            folded.append(frag)
            if over:
                over = _membudget.INGEST_DELTA_BUDGET.resident_bytes \
                    > limit
        if folded:
            with self._cond:
                for frag in folded:
                    self._journal_frags.pop(frag, None)

    def merge_all(self):
        """Fold every live overlay journal now (tests, drain)."""
        with self._cond:
            frags = list(self._journal_frags)
            self._journal_frags.clear()
        for frag in frags:
            if frag.fold_delta():
                self.folds += 1

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "flushMs": self.flush_ms,
                "pendingBytes": self._pend_bytes,
                "pendingRecords": self._pend_records,
                "flushes": self.flushes,
                "recordsTotal": self.records_total,
                "folds": self.folds,
                "journalFragments": len(self._journal_frags),
                "journalBytes":
                    _membudget.INGEST_DELTA_BUDGET.resident_bytes,
            }

    def close(self):
        """Final flush, then stop.  Journals stay live — fragment close
        folds through the normal snapshot path."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._flush_once()
