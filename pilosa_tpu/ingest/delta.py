"""Device-side delta overlay application (docs/ingest.md).

An ingest flush leaves its new words in the fragment's journal
(storage/fragment.py ingest_apply); resident device arrays absorb them
as a scatter-OR of a few KB instead of a re-upload of the whole dense
tensor.  Two consumers:

* per-fragment mirrors (``Fragment.device``) call ``apply_overlay``
  here — a plain single-device jit;
* mesh stacked blocks OR the journal inside a shard_map program
  (parallel/mesh_exec.py ``_apply_stack_overlay``), which reuses
  ``merge_chunks`` for the host-side prep.

The scatter is expressed as ``flat.at[idx].add(vals & ~flat[idx])`` —
an ADD of exactly the missing bits.  With host-deduplicated indices the
add equals the OR, and (unlike a plain ``.set``) it stays correct when
masked-out lanes collide on a dummy index, because adding zero commutes
with everything.  Indices travel as (row, word) int32 pairs, never a
flattened int64 — jax's default int width would silently truncate a
``row * 32768 + word`` offset past 2^31 on large fragments.
"""

from __future__ import annotations

import numpy as np


def merge_chunks(chunks) -> tuple[np.ndarray, np.ndarray]:
    """Combine journal chunks [(epoch, flat idx, val), ...] into unique
    sorted flat indices with OR-merged word values — the host dedupe
    that makes the device scatter collision-free."""
    if not chunks:
        z = np.zeros(0, dtype=np.int64)
        return z, z.astype(np.uint32)
    idx = np.concatenate([c[1] for c in chunks])
    val = np.concatenate([c[2] for c in chunks])
    uniq, inv = np.unique(idx, return_inverse=True)
    out = np.zeros(uniq.size, dtype=np.uint32)
    np.bitwise_or.at(out, inv, val)
    return uniq, out


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def pad_overlay(flat_idx: np.ndarray, vals: np.ndarray, words: int,
                member: np.ndarray | None = None):
    """(row int32, word int32, val uint32) arrays padded to a pow2
    length so one compiled scatter serves a bucket of overlay sizes;
    with ``member`` (the mesh path's stacked-row index per word) a
    fourth padded int32 array leads the tuple.  Padding lanes carry
    val 0 at (member 0, row 0, word 0) — their contribution ``0 & ~x``
    is zero, so colliding with a real lane is harmless."""
    k = _pow2(max(int(flat_idx.size), 1))
    row = np.zeros(k, dtype=np.int32)
    word = np.zeros(k, dtype=np.int32)
    val = np.zeros(k, dtype=np.uint32)
    n = flat_idx.size
    row[:n] = (flat_idx // words).astype(np.int32)
    word[:n] = (flat_idx % words).astype(np.int32)
    val[:n] = vals
    if member is None:
        return row, word, val
    m = np.zeros(k, dtype=np.int32)
    m[:n] = member
    return m, row, word, val


_JIT_CACHE: dict = {}


def apply_overlay(mirror, flat_idx: np.ndarray, vals: np.ndarray,
                  words: int):
    """OR deduplicated journal words into a dense [rows, words] device
    mirror; returns the updated array (the old one stays valid for any
    in-flight computation that captured it)."""
    import jax

    row, word, val = pad_overlay(flat_idx, vals, words)
    key = ("mirror", mirror.shape, row.size)
    fn = _JIT_CACHE.get(key)
    if fn is None:
        def body(m, r, w, v):
            cur = m[r, w]
            return m.at[r, w].add(v & ~cur)

        fn = _JIT_CACHE[key] = jax.jit(body)
    # index/value args stay uncommitted numpy: the computation follows
    # the mirror's (possibly committed) placement
    return fn(mirror, row, word, val)
