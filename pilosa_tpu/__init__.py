"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch rebuild of the capabilities of the reference engine (Pilosa, a
Go distributed bitmap index — see SURVEY.md): same data model (index / field /
view / 2^20-column shard / fragment), PQL query language, HTTP API and cluster
behavior, but executed on TPU: roaring container algebra becomes dense uint32
bitset kernels fused by XLA, fragments live in HBM, per-shard mapReduce
becomes shard_map over a device mesh with ICI collective reductions.
"""

__version__ = "0.1.0"

from .core import (  # noqa: F401
    SHARD_WIDTH,
    SHARD_WIDTH_EXP,
    SHARD_WORDS,
    WORD_BITS,
)
